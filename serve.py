#!/usr/bin/env python
"""Online serving driver: checkpoint → warmed ServeEngine → HTTP frontend.

The online counterpart of test.py's offline loop (ROADMAP north star:
"serves heavy traffic"): load a checkpoint (or ``--synthetic`` random
weights for smoke/CI), pre-compile every (bucket, batch) program, then
serve ``/predict`` with bucket-aware dynamic batching until SIGTERM/SIGINT.

    # smoke: synthetic weights, tiny buckets, TCP on 8321
    python serve.py --network resnet50 --synthetic --port 8321 \
        --cfg "tpu__SCALES=((96,128),)" --serve-batch 4 --max-delay-ms 20

    # production-shaped: real checkpoint, telemetry on
    python serve.py --network resnet101 --prefix model/e2e --epoch 10 \
        --port 8321 --serve-batch 8 --max-delay-ms 10 --telemetry-dir /tmp/t

Scale-out contract: one replica per host/chip set behind a load balancer
(the Predictor is single-controller by design — see its multiprocess
error); ``--max-queue`` bounds each replica's admission so overload
sheds as fast 503s the balancer can retry elsewhere, not as queue bloat.
"""

from __future__ import annotations

import argparse
import signal
import threading

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.eval import Predictor
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.serve import (ControllerOptions, ServeEngine, ServeOptions,
                               SLOController, make_server, warmup)
from mx_rcnn_tpu.tools.common import (add_common_args, apply_program_cache,
                                      config_from_args,
                                      eval_params_from_args,
                                      start_observability)


def parse_args():
    parser = argparse.ArgumentParser(
        description="Serve a Faster R-CNN network over HTTP")
    add_common_args(parser, train=False)
    parser.add_argument("--port", type=int, default=0,
                        help="TCP port for the HTTP frontend")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--unix-socket", default="", dest="unix_socket",
                        help="serve HTTP over this Unix socket instead of "
                             "TCP (tests, local sidecars)")
    parser.add_argument("--serve-batch", type=int, default=4,
                        dest="serve_batch",
                        help="images per forward — every batch is padded "
                             "to exactly this size (one program per "
                             "bucket)")
    parser.add_argument("--max-delay-ms", type=float, default=10.0,
                        dest="max_delay_ms",
                        help="flush a partial batch once its oldest "
                             "request has waited this long; THE latency/"
                             "throughput knob (0 = no coalescing wait)")
    parser.add_argument("--max-queue", type=int, default=64,
                        dest="max_queue",
                        help="bounded-queue backpressure: submits beyond "
                             "this many pending requests get 503")
    parser.add_argument("--deadline-ms", type=float, default=30000.0,
                        dest="deadline_ms",
                        help="default per-request deadline (504 when "
                             "exceeded; requests may override; <=0 "
                             "disables)")
    parser.add_argument("--target-p99-ms", type=float, default=0.0,
                        dest="target_p99_ms",
                        help="enable the SLO controller: adapt per-bucket "
                             "flush batch/delay toward this end-to-end "
                             "request-time p99 and shed load (503) when "
                             "the queue trend predicts misses (0 = off)")
    parser.add_argument("--slo-interval-ms", type=float, default=500.0,
                        dest="slo_interval_ms",
                        help="SLO controller tick period")
    parser.add_argument("--slo-window-s", type=float, default=10.0,
                        dest="slo_window_s",
                        help="trailing window the controller's p99 is "
                             "computed over")
    return parser.parse_args()


def main(args):
    if not args.unix_socket and not args.port:
        raise SystemExit("pass --port or --unix-socket")
    cfg = config_from_args(args, train=False)
    apply_program_cache(args)  # before the Predictor builds its registry
    model = build_model(cfg)
    params = eval_params_from_args(args, cfg, model)
    # the plane owns the sink (configure → summary → shutdown) and, with
    # --obs-port, the live Prometheus endpoint; the frontend's own
    # /metrics keeps serving regardless (JSON + ?format=prom)
    obs = start_observability(args, "serve",
                              run_meta={"network": args.network,
                                        "serve_batch": args.serve_batch,
                                        "max_delay_ms": args.max_delay_ms},
                              configure_telemetry=True)
    predictor = Predictor(model, params, cfg, dtype=args.infer_dtype)
    engine = ServeEngine(predictor, cfg, ServeOptions(
        batch_size=args.serve_batch, max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue, deadline_ms=args.deadline_ms,
        # the common --loader-workers flag doubles as the serving prep
        # pool size (same data/workers.py pool, image-only tasks)
        prep_workers=args.loader_workers or 0)).start()
    warmup(engine)
    controller = None
    if args.target_p99_ms > 0:
        controller = SLOController(engine, ControllerOptions(
            target_p99_ms=args.target_p99_ms,
            interval_s=args.slo_interval_ms / 1e3,
            window_s=args.slo_window_s)).start()

    server = make_server(engine, port=args.port or None, host=args.host,
                         unix_socket=args.unix_socket or None)
    # serve_forever on a worker thread; the main thread parks on an event
    # the signal handlers set — shutdown() called from the serving thread
    # itself would deadlock its poll loop
    done = threading.Event()

    def _on_signal(signum, frame):
        # flight-record the shutdown before draining — the ring holds the
        # last serve/* events if anything hangs past this point
        telemetry.get().dump_flight(
            "preempt_signal", signal=signal.Signals(signum).name)
        done.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    t = threading.Thread(target=server.serve_forever, name="serve-http",
                         daemon=True)
    t.start()
    where = args.unix_socket or f"http://{args.host}:{args.port}"
    logger.info("serving %s on %s (batch=%d, max_delay=%.0fms, "
                "max_queue=%d)", args.network, where, args.serve_batch,
                args.max_delay_ms, args.max_queue)
    done.wait()
    logger.info("shutting down: %s", engine.metrics()["counters"])
    server.shutdown()
    if controller is not None:
        controller.stop()
    engine.stop()
    obs.close(extra={"serve": engine.metrics()})


if __name__ == "__main__":
    main(parse_args())
