#!/usr/bin/env bash
# Elastic-autoscaling smoke (CPU-friendly): the ISSUE-18 capacity
# authority over a real localhost-TCP fabric with the real model and
# synthetic weights — one router with --autoscale plus TWO standalone
# TCP members that self-register with --join, sharing one AOT program
# cache so only the first boot compiles.
#
#   1. Idle drain — with the fleet bounded 1..2 and nothing to serve,
#      the authority parks one member back to the minimum.  The
#      Prometheus exposition must show the parked member in the
#      aggregate fabric_member_count{state=...} gauges (the satellite-1
#      fleet-size assert: one grep, no JSON parsing).
#   2. Flash crowd — scripts/loadgen.py --profile flashcrowd drives the
#      time-varying open-loop schedule (1× base rate, an 8× spike, then
#      1× again) while its FleetWatcher samples the router's
#      ready-member count.  The spike must UNPARK the warm spare
#      (member count tracks load), requests keep resolving, and the
#      authority's zero-recompile verification must pass: new capacity
#      warms from the shared AOT cache, params stay runtime args, so
#      the engines' recompile counters must not move.
#   3. Drain back — the crowd passes and the authority parks the spare
#      again: up on trend, down on hysteresis, no flapping in between
#      (thrash_freeze stays 0).
#
# The profile run lands as an mxr_autoscale_report (AUTOSCALE_r01.json)
# scored by scripts/perf_gate.py: fleet growth against the scale-up
# floor, time_to_scale_s against its ceiling, p99 through the scale
# events against the pinned ceiling, and recompiles against a ZERO
# ceiling — fleet_excess_recompiles folds the per-member registry
# counters (compiles beyond warmup) into the same zero-ceiling row.
#
#   bash script/autoscale_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${AUTOSCALE_SMOKE_DIR:-/tmp/mxr_autoscale_smoke}
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"   # shared AOT warm-start: 3 boots, 1 compile
tel="$dir/tel"

common=(--network resnet50 --synthetic --serve-batch 2 --max-delay-ms 20
        --max-queue 32 --deadline-ms 120000 --program-cache "$cache"
        --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
        --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

# three free localhost ports: router, member 0, member 1
read -r RP M0 M1 <<<"$(python - <<'EOF'
import socket
socks = [socket.socket() for _ in range(3)]
for s in socks:
    s.bind(("127.0.0.1", 0))
print(" ".join(str(s.getsockname()[1]) for s in socks))
for s in socks:
    s.close()
EOF
)"

# wait_fleet PORT PID WANT [OP]: poll the router's /readyz until the
# ready-member count reaches (default) or drops to WANT — the autoscaler
# moves the count in BOTH directions in this smoke
wait_fleet() {
python - "$1" "$2" "$3" "${4:-ge}" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, pid, want, op = (int(sys.argv[1]), int(sys.argv[2]),
                       int(sys.argv[3]), sys.argv[4])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("router exited before the fleet settled")
    try:
        _, doc = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                  timeout=5)
        n = doc.get("ready_members", 0)
        if (op == "ge" and n >= want) or (op == "le" and n <= want):
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit(f"fleet never settled at {op} {want} ready members")
EOF
}

# ---- fabric up: autoscaling router + 2 self-registering members ----------
echo "autoscale_smoke: [1/3] idle fleet drains to --autoscale-min"
python serve.py --network resnet50 --fabric --port "$RP" \
  --probe-interval-s 0.5 --telemetry-dir "$tel" \
  --autoscale --autoscale-min 1 --autoscale-max 2 \
  --autoscale-target-depth 2 --autoscale-interval-s 0.5 &
rpid=$!
mpids=()
for i in 0 1; do
  mports=("$M0" "$M1")
  MXR_REPLICA_INDEX=$i python serve.py "${common[@]}" \
    --port "${mports[i]}" --join "127.0.0.1:$RP" &
  mpids[i]=$!
done
trap 'kill "$rpid" "${mpids[@]}" 2>/dev/null || true' EXIT

wait_fleet "$RP" "$rpid" 2            # both members join and warm up
wait_fleet "$RP" "$rpid" 1 le         # ...then idle drains one to PARKED

# satellite 1: the Prometheus exposition answers "how big is the fleet,
# by state" with one labeled gauge family — assert it with a grep
curl -sf "http://127.0.0.1:$RP/metrics?format=prom" >"$dir/prom.txt" \
  || python - "$RP" "$dir/prom.txt" <<'EOF'
import sys
from mx_rcnn_tpu.serve import tcp_http_request_raw
status, raw, _ = tcp_http_request_raw(
    "127.0.0.1", int(sys.argv[1]), "GET", "/metrics?format=prom",
    headers={"Accept": "text/plain"}, timeout=10)
assert status == 200, status
open(sys.argv[2], "wb").write(raw)
EOF
grep -q 'fabric_member_count{state="parked"} 1' "$dir/prom.txt"
grep -q 'fabric_member_count{state="ready"} 1' "$dir/prom.txt"
echo "autoscale_smoke: parked spare visible in fabric_member_count gauges"

# ---- act 2: flash crowd → scale-up from the warm spare -------------------
echo "autoscale_smoke: [2/3] flash crowd unparks the spare"
python scripts/loadgen.py --port "$RP" --fabric --profile flashcrowd \
  --n 40 --rate 2 --short 80 --long 110 --fleet-poll-s 0.3 \
  --scale-floor 1 --time-to-scale-ceiling-s 90 --p99-ceiling-ms 60000 \
  --report "$dir/AUTOSCALE_r01.json" | tee "$dir/flashcrowd.json"

# the crowd scaled the fleet, nothing recompiled, nothing was dropped
python - "$dir/AUTOSCALE_r01.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "mxr_autoscale_report", doc["schema"]
row = doc["scenarios"][0]
assert row["profile"] == "flashcrowd", row
fleet = row["fleet"]
assert fleet["peak"] > fleet["start"], \
    f"the flash crowd never grew the fleet: {fleet}"
assert row["time_to_scale_s"] is not None, fleet
assert row["recompiles_during_run"] == 0, \
    f"scale-up COMPILED {row['recompiles_during_run']} program(s)"
sched = row["schedule"]
assert len(sched) == 3 and sched[1]["rate"] == 8 * 2.0, sched
print(f"autoscale_smoke: flash crowd OK (fleet {fleet['start']}→"
      f"{fleet['peak']}, time_to_scale_s={row['time_to_scale_s']}, "
      f"p99_ms={row['p99_ms']}, recompiles=0)")
EOF

# ---- act 3: crowd passes → drain back, authority stayed sane -------------
echo "autoscale_smoke: [3/3] load drop drains the fleet back down"
wait_fleet "$RP" "$rpid" 1 le

# authority pane: both directions acted, zero violations, zero thrash;
# per-member registry counters certify compiles == warmup only (the
# fleet_excess_recompiles fed to the gate's zero-ceiling row)
python - "$RP" "$M0" "$M1" "$dir/AUTOSCALE_r01.json" <<'EOF'
import json, sys
from mx_rcnn_tpu.serve import tcp_http_request
rp = int(sys.argv[1])
status, m = tcp_http_request("127.0.0.1", rp, "GET", "/metrics",
                             timeout=10)
assert status == 200, m
a = m.get("autoscale")
assert a, "router /metrics has no autoscale pane"
c = a["counters"]
assert c["scale_up"] >= 1 and c["unpark"] >= 1, c
assert c["scale_down"] >= 1 and c["park"] >= 1, c
assert c["recompile_violation"] == 0, c
assert c["recompile_check"] >= 1, c
assert c["thrash_freeze"] == 0, c
excess = 0
for port in (int(sys.argv[2]), int(sys.argv[3])):
    try:
        status, doc = tcp_http_request("127.0.0.1", port, "GET",
                                       "/metrics", timeout=10)
    except OSError:
        continue                 # the parked member still answers, but
    if status != 200:            # tolerate a mid-drain straggler
        continue
    counters = doc.get("counters") or {}
    excess += max(int(counters.get("recompiles", 0))
                  - int(counters.get("warmup_programs", 0)), 0)
assert excess == 0, f"{excess} compile(s) beyond warmup across the fleet"
doc = json.load(open(sys.argv[4]))
doc["fleet_excess_recompiles"] = excess
doc["recompile_ceiling"] = 0.0
doc["autoscale_counters"] = c    # ride-along context for the archive
json.dump(doc, open(sys.argv[4], "w"), indent=1, sort_keys=True)
print(f"autoscale_smoke: authority OK (scale_up={c['scale_up']}, "
      f"scale_down={c['scale_down']}, violations=0, excess_recompiles=0)")
EOF

kill -TERM "${mpids[@]}" "$rpid"
wait "$rpid" || true
wait "${mpids[@]}" || true
trap - EXIT

# every decision is first-class telemetry with the PR-16 trace plumbing
python - "$tel" <<'EOF'
import glob, json, sys
events = []
for path in glob.glob(f"{sys.argv[1]}/events_rank*.jsonl"):
    for line in open(path):
        events.append(json.loads(line))
decisions = [e for e in events
             if e.get("kind") == "meta" and e.get("name") == "autoscale_decision"]
assert decisions, "no autoscale_decision meta events in the stream"
acts = {d["fields"]["action"] for d in decisions}
assert any(a.startswith("scale_up") for a in acts), acts
assert any(a.startswith("scale_down") for a in acts), acts
print(f"autoscale_smoke: telemetry OK ({len(decisions)} decision "
      f"events, actions={sorted(acts)})")
EOF

# ---- perf gate -----------------------------------------------------------
python scripts/perf_gate.py --check-format "$dir"/AUTOSCALE_r*.json
python scripts/perf_gate.py --dir "$dir"
echo "autoscale_smoke: OK"
