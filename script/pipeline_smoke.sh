#!/usr/bin/env bash
# Pipeline tuner smoke: a tiny synthetic 2x2 sweep (k in {1,2} x workers in
# {0,2}) through bench.py --mode pipeline --auto-tune, then prove the whole
# contract end to end:
#   * every cell reports the loader_wait/dispatch/fetch_stall/assembly_wait
#     breakdown and the tuner persists the winning cell,
#   * the --sweep-out JSONL folds into scripts/telemetry_report.py's
#     "pipeline cell" table,
#   * the bench output wrapped as a BENCH_r06-shaped artifact passes
#     scripts/perf_gate.py --check-format,
#   * train_end2end.py --tuned-pipeline (same config) finds the persisted
#     cell and boots into it (the "tuned pipeline:" log line).
set -e
base=${PIPELINE_SMOKE_DIR:-/tmp/mxr_pipeline_smoke}
rm -rf "$base"
mkdir -p "$base"
export MXR_PROGRAM_CACHE="$base/cache"

# the tiny config shared by the sweep and the tuned boot: the tuned-cell
# key is a config digest, so both invocations must describe the SAME model
TINY_CFG=(--cfg "TRAIN__RPN_PRE_NMS_TOP_N=200" \
          --cfg "TRAIN__RPN_POST_NMS_TOP_N=32" \
          --cfg "TRAIN__BATCH_ROIS=16" \
          --cfg "tpu__SCALES=((64,96),)" \
          --cfg "tpu__MAX_GT=4" \
          --cfg "network__ANCHOR_SCALES=(2,4)")

python bench.py --mode pipeline --network resnet50 --auto-tune \
  --k-list 1,2 --workers-list 0,2 --prefetch-list 2 \
  --pipeline-images 8 --pipeline-epochs 1 \
  --sweep-out "$base/sweep.jsonl" "${TINY_CFG[@]}" \
  > "$base/bench_pipeline.json"

test -f "$base/cache/pipeline_tuned.json"
test -f "$base/sweep.jsonl"

python - "$base" <<'EOF'
import json, sys

base = sys.argv[1]
with open(f"{base}/bench_pipeline.json") as f:
    out = json.load(f)
pipe = out["pipeline"]
assert len(pipe["cells"]) == 4, [c["cell"] for c in pipe["cells"]]
for row in pipe["cells"]:
    for field in ("imgs_per_sec", "loader_wait_s", "dispatch_s",
                  "fetch_stall_s", "assembly_wait_s", "loader_wait_frac",
                  "loader_wait_ok"):
        assert field in row, (row.get("cell"), field)
best = max(pipe["cells"], key=lambda r: r["imgs_per_sec"])
assert pipe["best"]["cell"] == best["cell"]
assert pipe["tuned"]["k"] == best["k"], (pipe["tuned"], best)
with open(f"{base}/cache/pipeline_tuned.json") as f:
    doc = json.load(f)
assert doc["schema"] == "mxr-pipeline-tuned-v1"
assert len(doc["tuned"]) == 1
rows = [json.loads(l) for l in open(f"{base}/sweep.jsonl")]
assert len(rows) == 4
assert all(r["kind"] == "meta" and r["name"] == "pipeline_cell"
           for r in rows)
print(f"pipeline_smoke: tuner selected {best['cell']} "
      f"({best['imgs_per_sec']:.2f} imgs/s, "
      f"loader_wait {100 * best['loader_wait_frac']:.1f}%)")
EOF

# the sweep JSONL must fold into the report's pipeline table
python scripts/telemetry_report.py "$base/sweep.jsonl" | tee "$base/report.txt"
grep -q "pipeline cell" "$base/report.txt"

# BENCH trajectory shape: wrap the bench line like the driver does and
# format-check it alongside the checked-in trajectory
python - "$base" <<'EOF'
import json, sys

base = sys.argv[1]
with open(f"{base}/bench_pipeline.json") as f:
    parsed = json.load(f)
with open(f"{base}/BENCH_r06.json", "w") as f:
    json.dump({"n": 6, "cmd": "bench.py --mode pipeline (smoke)",
               "rc": 0, "tail": "", "parsed": parsed}, f, indent=1)
EOF
python scripts/perf_gate.py --check-format "$base/BENCH_r06.json"

# tuned boot: the train driver must find the persisted cell for the SAME
# config and log the tuned (k, workers, prefetch, device_prep) it applied
python train_end2end.py --network resnet50 --synthetic --synthetic_images 8 \
  --prefix "$base/ckpt" --end_epoch 1 --num-steps 2 --frequent 1 \
  --tuned-pipeline "${TINY_CFG[@]}" 2>&1 | tee "$base/train.log"
grep -q "tuned pipeline: k=" "$base/train.log"

echo "pipeline_smoke: OK"
