#!/usr/bin/env bash
# Golden-runway (SURVEY §4 golden-metric reproduction): probe for real
# VOC/COCO + pretrained weights, convert .pth -> .npz if needed, run every
# runnable golden recipe, and write GOLDEN.md comparing measured mAP/AP
# against BASELINE.md's anchors.  Safe to run any time: with nothing on
# disk it just reports what is missing.
set -e
cd "$(dirname "$0")/.."
python scripts/golden.py "$@"
