#!/usr/bin/env bash
# Cascade serving smoke (CPU-friendly), asserting the --cascade contract
# end to end on real servers:
#   1. BIG-ONLY baseline boot (cold --program-cache, single model with
#      the big deployment's config): steady loadgen records the
#      always-big imgs/sec the cascade's absolute floor derives from.
#   2. CASCADE boot (--models small,big --cascade small:big): a probe
#      pass collects per-request hardness from the "cascade" provenance
#      field and calibrates the threshold to the observed median — the
#      README's quantile-calibration workflow, executable.
#   3. WARM cascade boot at the calibrated threshold: loadgen --cascade
#      (big_only baseline scenario + gated scenario over identical
#      payloads) under --assert-2xx writes CASCADE_r01.json; the live
#      /metrics cascade section must show escalation_rate strictly
#      inside (0, 1) (the gate actually splits traffic at the median),
#      zero steady-state recompiles on BOTH engines, and — the warm-
#      boot acceptance — aot_hit == programs on the small model's
#      registry with the ``cascade_gate`` program among them: the gate
#      program rides the persistent cache like every fused forward.
#   4. scripts/perf_gate.py gates the trajectory including the new
#      CASCADE rows (speedup_vs_big floor, imgs_per_sec floor,
#      per-class latency trends).
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${CASCADE_SMOKE_DIR:-/tmp/mxr_cascade_smoke}
deadline_ms=60000
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"
tinycfg=(--cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
         --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)
# big = same network, one digest-changing override: the realistic
# small/big two-deployments-one-chip shape (disjoint AOT subtrees)
ccflags=(--serve-e2e --models small=resnet50,big=resnet50
         --model-arg "big:cfg=TEST__NMS=0.31")

wait_healthy() {
  python - "$1" "$2" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid = sys.argv[1], int(sys.argv[2])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, doc = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became healthy")
EOF
}

stop() {  # pid — TERM and poll until gone
  kill -TERM "$1" 2>/dev/null || true
  for _ in $(seq 1 100); do
    kill -0 "$1" 2>/dev/null || return 0
    sleep 0.2
  done
  kill -KILL "$1" 2>/dev/null || true
}

boot() {  # sock extra-flags... — start serve.py, echo its pid
  sock="$1"; shift
  python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
    --serve-batch 2 --max-delay-ms 50 --max-queue 64 \
    --deadline-ms "$deadline_ms" --program-cache "$cache" \
    "${tinycfg[@]}" "$@" >"$sock.log" 2>&1 &
  echo $!
}

# ---- 1. big-only baseline ------------------------------------------------
sock="$dir/bigonly.sock"
pid=$(boot "$sock" --serve-e2e --cfg TEST__NMS=0.31)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
python scripts/loadgen.py --unix-socket "$sock" --n 16 --rate 4 \
  --short 90 --long 120 --deadline-ms "$deadline_ms" --assert-2xx \
  | tee "$dir/bigonly.out"
stop "$pid"
base_tput=$(python - "$dir/bigonly.out" <<'EOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip().startswith("{")]
tput = rows[-1].get("imgs_per_sec")
assert isinstance(tput, (int, float)) and tput > 0, rows[-1]
print(tput)
EOF
)

# ---- 2. cascade boot: calibrate the threshold from live hardness ---------
sock="$dir/probe.sock"
pid=$(boot "$sock" "${ccflags[@]}" --cascade small:big --cascade-thresh 0.5)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
thresh=$(python - "$sock" <<'EOF'
import sys
import numpy as np
from mx_rcnn_tpu.flywheel.hardness import HARDNESS_MAX
from mx_rcnn_tpu.serve import encode_image_payload, unix_http_request
sock = sys.argv[1]
rng = np.random.RandomState(0)
hard = []
for i in range(8):
    h, w = (90, 120) if i % 2 == 0 else (120, 90)
    img = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
    status, resp = unix_http_request(sock, "POST", "/predict",
                                    encode_image_payload(img), timeout=600)
    assert status == 200, resp
    prov = resp.get("cascade") or {}
    assert "hardness" in prov, prov  # every gated answer carries it
    hard.append(float(prov["hardness"]))
# the README workflow: pick the quantile that splits the traffic —
# thresh at the observed median => escalation_rate ~ 0.5
t = float(np.median(hard)) / HARDNESS_MAX
print(round(min(max(t, 0.02), 0.98), 4))
EOF
)
stop "$pid"
echo "calibrated --cascade-thresh $thresh from live hardness"

# ---- 3. warm cascade boot at the calibrated threshold --------------------
sock="$dir/cascade.sock"
pid=$(boot "$sock" "${ccflags[@]}" --cascade small:big \
      --cascade-thresh "$thresh")
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"

# the cascade must clear an absolute floor too — generous on a shared CI
# box (the property is that the row is wired, not the number): the gated
# pass may escalate ~half the frames, so 30% of always-big is safe
floor=$(python -c "print(round(0.3 * float('$base_tput'), 3))")
python scripts/loadgen.py --unix-socket "$sock" --n 24 --rate 4 \
  --short 90 --long 120 --deadline-ms "$deadline_ms" --cascade \
  --speedup-floor 0.05 --throughput-floor "$floor" --assert-2xx \
  --report "${CASCADE_OUT:-CASCADE_r01.json}" \
  | tee "$dir/cascade.out"

python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200 and "cascade" in m, sorted(m)
c = m["cascade"]
assert c["small"] == "small" and c["big"] == "big", c
dec = c["counters"]["answered_small"] + c["counters"]["escalated"]
assert dec > 0, c["counters"]
# the live acceptance: the calibrated gate actually SPLITS the traffic
assert 0.0 < c["escalation_rate"] < 1.0, c
assert c["latency"].get("gate_time_p99_ms") is not None, c["latency"]
for mid in ("small", "big"):
    ec = m["models"][mid]["counters"]
    assert ec["recompiles"] == ec["warmup_programs"], (mid, ec)
# warm-boot acceptance: every program — fused forwards AND the
# cascade_gate — served from the persistent cache
small = m["models"]["small"]["compile"]
kinds = {p["kind"] for p in small["programs"]}
assert "cascade_gate" in kinds, kinds
rc = small["counters"]
assert rc["aot_hit"] == rc["programs"] and rc["programs"] > 0, rc
print(f"cascade metrics ok: escalation_rate={c['escalation_rate']} "
      f"({c['counters']['escalated']}/{dec} escalated), 0 steady-state "
      f"recompiles, {rc['aot_hit']}/{rc['programs']} programs incl. "
      f"cascade_gate from the persistent cache")
EOF
stop "$pid"
trap - EXIT

# ---- 4. gate the trajectory including the cascade rows -------------------
python scripts/perf_gate.py
echo "cascade smoke ok"
