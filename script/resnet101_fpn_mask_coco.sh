#!/usr/bin/env bash
# Mask R-CNN (ResNet-101-FPN) on COCO (BASELINE.json config 5).
# Mask configs train end2end only (the alternate pipeline has no
# mask-target path — see models/fpn.py:rcnn_train).
set -e
# --steps-per-dispatch 4: same scanned-dispatch layout win as the FPN
# recipe (the mask graph shares the pyramid; measured on the FPN step,
# BASELINE.md round-4 ledger)
python train_end2end.py --network resnet101_fpn_mask --dataset coco \
  --pretrained model/resnet101.npz --steps-per-dispatch 4 \
  --prefix model/mask_coco --end_epoch 7 --lr 0.00125 --lr_step 5,6 "$@"
python test.py --network resnet101_fpn_mask --dataset coco \
  --prefix model/mask_coco --epoch 7
