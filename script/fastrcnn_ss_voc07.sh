#!/usr/bin/env bash
# Legacy Fast-RCNN: train the box head on precomputed selective-search
# proposals (the reference's selective_search_roidb path).  Expects the rbg
# release at data/selective_search_data/voc_2007_trainval.mat and a
# converted VGG-16 at model/vgg16.npz.
set -e
python -m mx_rcnn_tpu.tools.train_rcnn --network vgg16 --dataset PascalVOC \
  --image_set 2007_trainval --proposals selective_search \
  --pretrained model/vgg16.npz \
  --prefix model/fastrcnn_ss --end_epoch 10 --lr 0.001 --lr_step 7 "$@"
