#!/usr/bin/env bash
# Streaming serving smoke (CPU-friendly), asserting the --stream
# contract end to end on a real server:
#   1. GATE-OFF boot (--stream, threshold 0, cold --program-cache):
#      /stream answers byte-identically to /predict for the same pixels
#      (pure coalescing must not change a single byte), then a static
#      4-stream closed-loop loadgen run records the gate-off
#      dispatches_per_frame reference.
#   2. GATE-ON boot (--stream-skip-thresh 3 --stream-max-skip 16, same
#      cache): the same static profile must skip (skip_fraction above
#      the --skip-floor) and cut dispatches_per_frame by >= 3x vs the
#      gate-off reference, with zero steady-state recompiles
#      (recompiles == warmup_programs) and the compile snapshot
#      labeling one frame_delta program per orientation bucket.
#      Writes STREAM_r01.json (mxr_stream_report) for the gate.
#   3. SECOND gate-on boot over the now-warm cache: EVERY program —
#      fused forwards and frame_delta gates alike — is an AOT hit
#      (aot_hit == programs), so streaming adds zero cold-start cost.
#   4. scripts/perf_gate.py gates the trajectory including the new
#      stream rows (skip_fraction floor, per-stream p99 ceiling).
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${STREAM_SMOKE_DIR:-/tmp/mxr_stream_smoke}
deadline_ms=60000
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"
tinycfg=(--cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
         --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

wait_healthy() {
  python - "$1" "$2" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid = sys.argv[1], int(sys.argv[2])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, doc = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became healthy")
EOF
}

stop() {  # pid — TERM and poll until gone (the server is a subshell
  # child, so ``wait`` can't reap it here)
  kill -TERM "$1" 2>/dev/null || true
  for _ in $(seq 1 100); do
    kill -0 "$1" 2>/dev/null || return 0
    sleep 0.2
  done
  kill -KILL "$1" 2>/dev/null || true
}

boot() {  # sock extra-flags... — start serve.py, echo its pid
  sock="$1"; shift
  python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
    --serve-batch 2 --max-delay-ms 50 --max-queue 64 \
    --deadline-ms "$deadline_ms" --program-cache "$cache" --serve-e2e \
    "${tinycfg[@]}" "$@" >"$sock.log" 2>&1 &
  echo $!
}

dpf_of() {  # loadgen-stdout-file — the static scenario's dispatches_per_frame
  python - "$1" <<'EOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip().startswith("{")]
row = [r for r in rows if r.get("scenario") == "static"][-1]
dpf = row.get("dispatches_per_frame")
assert isinstance(dpf, (int, float)) and dpf > 0, row
print(dpf)
EOF
}

# ---- 1. gate-off boot: byte parity + dispatch reference ------------------
sock="$dir/off.sock"
pid=$(boot "$sock" --stream)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"

python - "$sock" <<'EOF'
import json, sys
import numpy as np
from mx_rcnn_tpu.serve import encode_image_payload
from mx_rcnn_tpu.serve.frontend import unix_http_request, unix_http_request_raw
sock = sys.argv[1]
rng = np.random.RandomState(3)
frames = [rng.randint(0, 255, (80, 110, 3), dtype=np.uint8) for _ in range(3)]
# the reference: each frame as an independent /predict request
ref = []
for img in frames:
    status, resp = unix_http_request(sock, "POST", "/predict",
                                     encode_image_payload(img), timeout=300)
    assert status == 200, resp
    ref.append(resp["detections"])
# the same pixels as one pipelined /stream burst — gate off, so the
# responses must be BYTE-identical to the /predict path
body = "\n".join(
    json.dumps({"stream_id": "parity", "seq": i + 1,
                **encode_image_payload(img)})
    for i, img in enumerate(frames)).encode()
status, raw, ctype = unix_http_request_raw(sock, "POST", "/stream", body,
                                           timeout=300)
assert status == 200 and "ndjson" in ctype, (status, ctype)
replies = [json.loads(l) for l in raw.decode().splitlines()]
assert [r["status"] for r in replies] == [200, 200, 200], replies
for i, (r, dets) in enumerate(zip(replies, ref)):
    assert r["seq"] == i + 1 and r["skipped"] is False, r
    assert json.dumps(r["detections"], sort_keys=True) \
        == json.dumps(dets, sort_keys=True), f"frame {i} diverged"
print(f"gate-off parity ok: {len(frames)} frame(s) byte-identical "
      "to /predict")
EOF

python scripts/loadgen.py --unix-socket "$sock" --streams 4 --fps 4 \
  --frames 16 --motion static --deadline-ms "$deadline_ms" \
  | tee "$dir/off.out"
off_dpf=$(dpf_of "$dir/off.out")
stop "$pid"

# ---- 2. gate-on boot: the skip gate must pay for itself ------------------
sock="$dir/on.sock"
pid=$(boot "$sock" --stream --stream-skip-thresh 3 --stream-max-skip 16)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"

python scripts/loadgen.py --unix-socket "$sock" --streams 4 --fps 4 \
  --frames 16 --motion static --deadline-ms "$deadline_ms" \
  --skip-floor 0.5 --p99-ceiling-ms 30000 --assert-2xx \
  --report "${STREAM_OUT:-STREAM_r01.json}" \
  | tee "$dir/on.out"
on_dpf=$(dpf_of "$dir/on.out")

python - "$off_dpf" "$on_dpf" <<'EOF'
import sys
off, on = float(sys.argv[1]), float(sys.argv[2])
# the tentpole's acceptance: the gate cuts device work >= 3x on a
# static profile vs the identical gate-off stream set
assert on > 0 and off / on >= 3.0, \
    f"dispatches_per_frame {off} -> {on}: less than the required 3x win"
print(f"skip win ok: dispatches_per_frame {off} -> {on} "
      f"({off / on:.1f}x fewer dispatches)")
EOF

python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200
c, st = m["counters"], m["stream"]
assert st["counters"]["skipped"] > 0, st
assert st["counters"]["frames"] > 0, st
assert st["skip_fraction"] > 0, st
# zero steady-state recompiles: streaming traffic over the warm AOT
# cache compiled nothing beyond warmup, and the gate programs are
# ordinary kind-labeled registry citizens (one per orientation)
assert c["recompiles"] == c["warmup_programs"], c
rows = m["compile"]["programs"]
assert sum(p["kind"] == "frame_delta" for p in rows) == 2, rows
print(f"gate-on metrics ok: skip_fraction={st['skip_fraction']}, "
      f"{st['counters']['skipped']}/{st['counters']['frames']} frames "
      f"skipped, 0 steady-state recompiles")
EOF
stop "$pid"

# ---- 3. warm gate-on boot: the gate programs AOT-hit like the rest -------
sock="$dir/warm.sock"
pid=$(boot "$sock" --stream --stream-skip-thresh 3 --stream-max-skip 16)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200
rc = m["compile"]["counters"]
kinds = {p["kind"] for p in m["compile"]["programs"]}
assert "frame_delta" in kinds, kinds
assert rc["programs"] > 0
assert rc["aot_hit"] == rc["programs"], rc
print(f"aot warm start ok: {rc['aot_hit']}/{rc['programs']} program(s) "
      f"incl. frame_delta served from the persistent cache")
EOF
stop "$pid"
trap - EXIT

# ---- 4. gate the trajectory including the stream rows --------------------
python scripts/perf_gate.py
echo "stream smoke ok"
