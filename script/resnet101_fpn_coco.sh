#!/usr/bin/env bash
# ResNet-101-FPN Faster R-CNN on COCO (BASELINE.json config 4).
# Expects COCO under data/coco (train2017/val2017 + annotations) and a
# converted backbone at model/resnet101.npz (utils/convert_torch.py).
set -e
# --steps-per-dispatch 4: the FPN step drops 21.95 -> 17.85 ms inside a
# scanned multi-step program (better P2-conv layout; BASELINE.md round-4
# ledger), and group assembly rides the prefetch thread so the transfer
# overlap of k=1 is kept
python train_end2end.py --network resnet101_fpn --dataset coco \
  --pretrained model/resnet101.npz --steps-per-dispatch 4 \
  --prefix model/fpn_coco --end_epoch 7 --lr 0.00125 --lr_step 5,6 "$@"
python test.py --network resnet101_fpn --dataset coco \
  --prefix model/fpn_coco --epoch 7
