#!/usr/bin/env bash
# Fault-tolerance smoke, through the real CLI driver (tests/faults.py covers
# the in-process paths; this exercises the env-driven injectors + signals):
#
#   run 1  synthetic train, SIGTERM'd once the first mid-epoch step
#          checkpoint lands -> must exit cleanly (preemption save)
#   run 2  the SAME command again — --auto-resume picks the step checkpoint,
#          zero manual flags -> must complete every epoch
#   run 3  one injected bad roidb record (MXR_FAULT_BAD_RECORD) + one
#          injected NaN step (MXR_FAULT_NAN_STEP) under --nan-policy
#          rollback -> must finish, with every recovery counter visible in
#          scripts/telemetry_report.py's "recovery event" section
set -e

ckpt=${FAULT_CKPT:-/tmp/mxr_fault_smoke_ckpt}
ckpt3=${FAULT_CKPT3:-/tmp/mxr_fault_smoke_ckpt3}
tel1=${FAULT_TEL1:-/tmp/mxr_fault_smoke_tel1}
tel2=${FAULT_TEL2:-/tmp/mxr_fault_smoke_tel2}
tel3=${FAULT_TEL3:-/tmp/mxr_fault_smoke_tel3}
rm -rf "$ckpt" "$ckpt3" "$tel1" "$tel2" "$tel3"

# tiny synthetic config (the tests' shapes) so the smoke compiles fast
base=(--network resnet50 --synthetic --synthetic_images 16
  --cfg "tpu__SCALES=((64,96),)" --cfg "tpu__MAX_GT=4"
  --cfg "network__ANCHOR_SCALES=(2,4)"
  --cfg "TRAIN__RPN_PRE_NMS_TOP_N=200"
  --cfg "TRAIN__RPN_POST_NMS_TOP_N=32"
  --cfg "TRAIN__BATCH_ROIS=16"
  --frequent 1 "$@")

echo "== run 1: train until the first step checkpoint, then SIGTERM =="
python train_end2end.py "${base[@]}" --prefix "$ckpt" --end_epoch 2 \
  --save-every-n-steps 4 --auto-resume --telemetry-dir "$tel1" &
pid=$!
for _ in $(seq 1 1200); do
  kill -0 "$pid" 2>/dev/null || break
  # any entry under steps/ (orbax tmp dirs included) = a step save started
  if ls "$ckpt/steps" 2>/dev/null | grep -q '[0-9]'; then break; fi
  sleep 0.5
done
kill -TERM "$pid" 2>/dev/null || true
wait "$pid"   # non-zero = the preemption path did NOT exit cleanly

echo "== run 2: same command, --auto-resume continues from the step ckpt =="
python train_end2end.py "${base[@]}" --prefix "$ckpt" --end_epoch 2 \
  --save-every-n-steps 4 --auto-resume --telemetry-dir "$tel2"
python - "$ckpt" <<'EOF'
import sys
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
eps = CheckpointManager(sys.argv[1]).available_epochs()
assert 2 in eps, f"auto-resume did not complete: epochs present {eps}"
print("auto-resume completed; epochs present:", eps)
EOF

echo "== run 1 telemetry: preemption recorded =="
python scripts/telemetry_report.py "$tel1" | tee /tmp/mxr_fault_smoke_r1.txt
grep -E '^train/preempted +[1-9]' /tmp/mxr_fault_smoke_r1.txt

echo "== run 3: injected bad record + NaN step under --nan-policy rollback =="
MXR_FAULT_BAD_RECORD=3 MXR_FAULT_NAN_STEP=6 \
python train_end2end.py "${base[@]}" --prefix "$ckpt3" --end_epoch 1 \
  --nan-policy rollback --save-every-n-steps 2 --telemetry-dir "$tel3"
python scripts/telemetry_report.py "$tel3" | tee /tmp/mxr_fault_smoke_r3.txt
grep -E '^loader/bad_record +[1-9]' /tmp/mxr_fault_smoke_r3.txt
grep -E '^train/nan_detected +[1-9]' /tmp/mxr_fault_smoke_r3.txt
grep -E '^train/nan_rollback +[1-9]' /tmp/mxr_fault_smoke_r3.txt

echo "fault smoke OK"
