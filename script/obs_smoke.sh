#!/usr/bin/env bash
# Observability-plane smoke: a synthetic train with --obs-port on, scraped
# over real HTTP WHILE it runs; then the trace export and the perf gate's
# format check over the checked-in bench trajectory.
#
#   bash script/obs_smoke.sh            # defaults: port 8377, /tmp dirs
#   OBS_PORT=9000 bash script/obs_smoke.sh
set -e
dir=${TELEMETRY_DIR:-/tmp/mxr_obs_smoke}
port=${OBS_PORT:-8377}
rm -rf "$dir"

# trace mode on so the span events carry wall-clock starts for the
# timeline export below
MXR_TELEMETRY_TRACE=1 python train_end2end.py --network resnet50 \
  --synthetic --synthetic_images 8 --prefix /tmp/mxr_obs_smoke_ckpt \
  --end_epoch 1 --num-steps 4 --frequent 1 \
  --telemetry-dir "$dir" --obs-port "$port" "$@" &
train_pid=$!
trap 'kill $train_pid 2>/dev/null || true' EXIT

# poll /metrics until the server is up and the first step's families are
# there (train/loader_wait is recorded before the first dispatch even
# finishes compiling, so a mid-run scrape always has it)
scrape=""
for _ in $(seq 1 120); do
  if scrape=$(curl -sf "http://127.0.0.1:$port/metrics" 2>/dev/null) \
     && grep -q "mxr_train_loader_wait_seconds_total" <<<"$scrape"; then
    break
  fi
  scrape=""
  sleep 0.5
done
test -n "$scrape" || { echo "obs_smoke: never scraped /metrics mid-run" >&2; exit 1; }
grep -q 'mxr_up{rank="0"} 1' <<<"$scrape"
grep -q 'mxr_train_loader_wait_seconds_total{rank="0"}' <<<"$scrape"
curl -sf "http://127.0.0.1:$port/healthz" | grep -q '"status": "ok"'
echo "obs_smoke: live scrape OK"

wait $train_pid
trap - EXIT
test -f "$dir/events_rank0.jsonl"
test -f "$dir/summary.json"

# the port must be released once the driver exits (plane teardown)
if curl -sf --max-time 2 "http://127.0.0.1:$port/healthz" >/dev/null 2>&1; then
  echo "obs_smoke: obs server still bound after exit" >&2; exit 1
fi

# fold the run into a Perfetto timeline and validate it is real JSON
python scripts/telemetry_report.py "$dir" --trace "$dir/trace.json"
python - "$dir/trace.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
events = doc["traceEvents"]
assert events, "empty trace"
assert any(e.get("ph") == "X" for e in events), "no span events"
print(f"obs_smoke: trace OK ({len(events)} events)")
EOF

# the perf gate must accept the checked-in bench trajectory
python scripts/perf_gate.py --check-format BENCH_r*.json
python scripts/perf_gate.py
echo "obs_smoke: OK"
