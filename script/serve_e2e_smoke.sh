#!/usr/bin/env bash
# Fused single-dispatch serving smoke (CPU-friendly), asserting the
# --serve-e2e contract end to end:
#   1. UNFUSED boot over a fresh --program-cache: record reference
#      detections for fixed pixels (the PR-3 path).
#   2. FUSED boot (--serve-e2e): scripts/loadgen.py --assert-2xx, fused
#      detection records match the unfused reference at float tolerance
#      (exact score ties at the MAX_PER_IMAGE cap are the documented
#      divergence), and the single-dispatch accounting holds:
#      h2d_transfers == dispatches == readbacks == batches, with the
#      compile snapshot labeling every new program kind "serve_e2e".
#   3. SECOND fused boot over the now-warm cache: every warmup program
#      is an AOT hit — aot_hit == warmup_programs, zero cold compiles.
#   4. bench.py --mode serve --serve-e2e emits the BENCH_r08 row
#      (readback_bytes_per_image / host_prep_ms ride along) and
#      scripts/perf_gate.py gates the trajectory including it.
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${SERVE_E2E_SMOKE_DIR:-/tmp/mxr_serve_e2e_smoke}
deadline_ms=60000
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"
tinycfg=(--cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
         --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

wait_healthy() {
  python - "$1" "$2" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid = sys.argv[1], int(sys.argv[2])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, doc = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became healthy")
EOF
}

predict_fixed() {  # sock out.json — POST the fixed pixels, save detections
  python - "$1" "$2" <<'EOF'
import json, sys
import numpy as np
from mx_rcnn_tpu.serve import encode_image_payload, unix_http_request
sock, out = sys.argv[1], sys.argv[2]
img = np.random.RandomState(3).randint(0, 255, (80, 110, 3), dtype=np.uint8)
status, resp = unix_http_request(sock, "POST", "/predict",
                                 encode_image_payload(img), timeout=300)
assert status == 200, resp
json.dump(resp["detections"], open(out, "w"))
EOF
}

stop() {  # pid — TERM and poll until gone (the server is a subshell
  # child, so ``wait`` can't reap it here)
  kill -TERM "$1" 2>/dev/null || true
  for _ in $(seq 1 100); do
    kill -0 "$1" 2>/dev/null || return 0
    sleep 0.2
  done
  kill -KILL "$1" 2>/dev/null || true
}

boot() {  # sock extra-flags... — start serve.py, echo its pid
  # server output goes to its own log: the caller captures this
  # function's stdout, and an inherited pipe would never reach EOF
  sock="$1"; shift
  python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
    --serve-batch 2 --max-delay-ms 50 --max-queue 32 \
    --deadline-ms "$deadline_ms" --program-cache "$cache" \
    "${tinycfg[@]}" "$@" >"$sock.log" 2>&1 &
  echo $!
}

# ---- 1. unfused reference boot (cold cache) ------------------------------
sock="$dir/ref.sock"
pid=$(boot "$sock")
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
predict_fixed "$sock" "$dir/ref.json"
stop "$pid"

# ---- 2. fused boot: load, parity diff, boundary accounting ---------------
sock="$dir/e2e.sock"
pid=$(boot "$sock" --serve-e2e)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"

python scripts/loadgen.py --unix-socket "$sock" --n 16 --rate 4 \
  --deadline-ms "$deadline_ms" --short 80 --long 110 --assert-2xx \
  | tee "$dir/loadgen.json"

predict_fixed "$sock" "$dir/e2e.json"
python - "$dir/ref.json" "$dir/e2e.json" <<'EOF'
import json, sys
import numpy as np
ref = json.load(open(sys.argv[1]))
e2e = json.load(open(sys.argv[2]))
# fused vs unfused detection records at float tolerance; exact score
# ties at the MAX_PER_IMAGE cap are the one documented divergence
assert len(ref) == len(e2e), (len(ref), len(e2e))
for r, f in zip(ref, e2e):
    assert r["cls"] == f["cls"], (r, f)
    assert abs(r["score"] - f["score"]) < 0.02, (r, f)
    assert np.allclose(r["bbox"], f["bbox"], atol=1.0), (r, f)
print(f"fused/unfused parity ok: {len(e2e)} detection record(s) match")
EOF

python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200
c = m["counters"]
# the single-dispatch contract: every batch crossed the boundary exactly
# once in each direction
assert c["h2d_transfers"] == c["dispatches"] == c["readbacks"] \
    == c["batches"] > 0, c
assert c["recompiles"] == c["warmup_programs"], c
rows = m["compile"]["programs"]
kinds = {p["kind"] for p in rows}
assert "serve_e2e" in kinds, kinds
per_img = c["readback_bytes"] / max(c["served"], 1)
print(f"single-dispatch ok: {c['batches']} batch(es), "
      f"{per_img:.0f} readback bytes/img, kinds={sorted(kinds)}")
EOF
stop "$pid"

# ---- 3. warm fused boot: AOT warm start under the new kind ---------------
sock="$dir/warm.sock"
pid=$(boot "$sock" --serve-e2e)
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200
c, rc = m["counters"], m["compile"]["counters"]
assert c["warmup_programs"] > 0
assert rc["aot_hit"] == c["warmup_programs"], (rc, c)
print(f"aot warm start ok: {rc['aot_hit']}/{c['warmup_programs']} "
      f"warmup program(s) served from the persistent cache")
EOF
stop "$pid"
trap - EXIT

# ---- 4. BENCH_r08 row + perf gate ----------------------------------------
bench_cmd=(python bench.py --mode serve --batch 2 --serve-e2e
           --network resnet50 "${tinycfg[@]}" --cfg tpu__MAX_GT=8)
"${bench_cmd[@]}" | tee "$dir/bench.out"
python - "$dir/bench.out" "${BENCH_OUT:-BENCH_r08.json}" <<EOF
import json, sys
lines = [l for l in open(sys.argv[1]) if l.strip().startswith("{")]
parsed = json.loads(lines[-1])
row = {"n": 8,
       "cmd": "JAX_PLATFORMS=cpu ${bench_cmd[*]}",
       "rc": 0, "tail": "", "parsed": parsed,
       "note": "serve_e2e fused path (script/serve_e2e_smoke.sh): its own "
               "metric series (serve_imgs_per_sec_e2e) so the gate never "
               "scores fused vs unfused; readback_bytes_per_image and "
               "host_prep_ms are the direction=down rows the fused path "
               "claims (CPU dev box — the wall-clock win is a TPU claim)"}
json.dump(row, open(sys.argv[2], "w"), indent=1)
print(f"wrote {sys.argv[2]}: {parsed['metric']}={parsed['value']} "
      f"readback_bytes_per_image={parsed.get('readback_bytes_per_image')} "
      f"host_prep_ms={parsed.get('host_prep_ms')}")
EOF
python scripts/perf_gate.py
echo "serve_e2e smoke ok"
