#!/usr/bin/env bash
# Reference recipe parity (script/resnet_voc07.sh): ResNet-101 Faster R-CNN
# end2end on VOC07 trainval, eval on VOC07 test.
set -e
python train_end2end.py --network resnet101 --dataset PascalVOC \
  --pretrained model/resnet101_imagenet.npz \
  --prefix model/resnet101_voc07_e2e --end_epoch 10 --lr 0.001 --lr_step 7 "$@"
python test.py --network resnet101 --dataset PascalVOC \
  --prefix model/resnet101_voc07_e2e --epoch 10
