#!/usr/bin/env bash
# 4-step alternate training (reference script/vgg_alter_voc07.sh).
set -e
python train_alternate.py --network vgg16 --dataset PascalVOC \
  --pretrained model/vgg16_imagenet.npz \
  --prefix model/vgg16_voc07_alt --end_epoch 8 "$@"
