#!/usr/bin/env bash
# Fleet-flywheel smoke (ISSUE 17, CPU-friendly): chaos-certified
# continuous learning at fabric scale, end to end through the real CLI
# drivers.
#
#   1. Fabric up — one router plus TWO standalone TCP members that
#      self-register with --join, both spilling request captures into
#      ONE shared capture dir (--capture-dir + --capture-member, the
#      member+pid shard grammar).  Member m0 runs with the
#      MXR_FAULT_FLYWHEEL_DUP_MANIFEST injection: every capture
#      manifest it publishes is delivered TWICE (the at-least-once
#      shape the merge must fold to one member entry).
#   2. Traffic — scripts/loadgen.py drives the router until both
#      members have spilled shards; the pre-promotion generation is
#      snapshotted off the router's /metrics.
#   3. Fleet round — flywheel.py fleet merges the per-member manifests
#      (duplicates dropped, not double-counted), folds the per-member
#      rankings into one global top-K with held-out eval entries,
#      replay-trains a real checkpoint into --ckpt-prefix, and promotes
#      it fleet-wide over the router's /admin/reload GATED on the
#      eval-shard quality check (generous --quality-slack: the
#      incumbent authored the pseudo-labels, the gate machinery — not
#      a tight delta — is what this smoke certifies).
#   4. Certify — generation advanced on the router AND on every member,
#      the fleet still serves clean 2xx traffic, and the run emits
#      FLYWHEEL_r02.json (schema mxr_flywheel_report) whose ADDITIVE
#      fleet fields (generation_promoted — a perf-gate FLOOR —
#      promotion_gate_pass, drift_detected, members) pass
#      scripts/perf_gate.py --check-format next to an r01 report.
#
#   bash script/flywheel_fleet_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${FLYWHEEL_FLEET_SMOKE_DIR:-/tmp/mxr_flywheel_fleet_smoke}
rm -rf "$dir"
mkdir -p "$dir"
cap="$dir/capture"
ckpt="$dir/ckpt"
cache="$dir/program_cache"   # shared AOT warm-start: 3 boots, 1 compile
telf="$dir/tel_fleet"
mkdir -p "$ckpt"

common=(--network resnet50 --synthetic --serve-batch 2 --max-delay-ms 20
        --max-queue 32 --deadline-ms 120000 --program-cache "$cache"
        --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
        --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

# three free localhost ports: router, member 0, member 1
read -r RP M0 M1 <<<"$(python - <<'EOF'
import socket
socks = [socket.socket() for _ in range(3)]
for s in socks:
    s.bind(("127.0.0.1", 0))
print(" ".join(str(s.getsockname()[1]) for s in socks))
for s in socks:
    s.close()
EOF
)"

wait_ready() {
python - "$1" "$2" "$3" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, pid, want = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("server exited before becoming ready")
    try:
        status, doc = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                       timeout=5)
        if want <= 1 and status == 200:
            sys.exit(0)
        if want > 1 and doc.get("ready_members", 0) >= want:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("server never became ready")
EOF
}

# ---- act 1: fabric up, shared capture dir, one injected fault ------------
echo "flywheel_fleet_smoke: [1/4] router + 2 capturing members" \
     "(m0 under dup-manifest injection)"
python serve.py --network resnet50 --fabric --port "$RP" \
  --probe-interval-s 1 --telemetry-dir "$telf" &
rpid=$!
MXR_REPLICA_INDEX=0 MXR_FAULT_FLYWHEEL_DUP_MANIFEST=m0 \
  python serve.py "${common[@]}" --port "$M0" --join "127.0.0.1:$RP" \
  --capture-dir "$cap" --capture-member m0 --capture-shard-records 8 &
m0pid=$!
MXR_REPLICA_INDEX=1 python serve.py "${common[@]}" --port "$M1" \
  --join "127.0.0.1:$RP" \
  --capture-dir "$cap" --capture-member m1 --capture-shard-records 8 &
m1pid=$!
trap 'kill "$rpid" "$m0pid" "$m1pid" 2>/dev/null || true' EXIT
wait_ready "$RP" "$rpid" 2

# ---- act 2: traffic until both members have spilled ----------------------
echo "flywheel_fleet_smoke: [2/4] loadgen until both members spilled"
python scripts/loadgen.py --port "$RP" --n 48 --rate 20 \
  --short 80 --long 110 --assert-2xx | tee "$dir/traffic.json"

python - "$RP" "$cap" "$dir" <<'EOF'
import json, sys, time
from mx_rcnn_tpu.flywheel import merge_manifests
from mx_rcnn_tpu.serve import tcp_http_request
port, cap, d = int(sys.argv[1]), sys.argv[2], sys.argv[3]
deadline = time.time() + 120
while True:
    merged = merge_manifests(cap)
    per = {m["member"]: len(m["shards"]) for m in merged["members"].values()}
    if per.get("m0", 0) >= 1 and per.get("m1", 0) >= 1:
        break
    if time.time() > deadline:
        sys.exit(f"both members never spilled: {per}")
    time.sleep(1)
# the injected duplicate delivery is on disk and folds to ONE entry
assert merged["duplicates_dropped"] >= 1, merged
status, m = tcp_http_request("127.0.0.1", port, "GET", "/metrics",
                             timeout=10)
assert status == 200, m
fw = m.get("flywheel") or {}
captured = sum(e.get("flywheel", {}).get("captured", 0)
               for e in m.get("engines", {}).values()) or fw.get("captured", 0)
snap = {"captured": captured,
        "generation_before": m["fabric"]["generation"],
        "duplicates_dropped": merged["duplicates_dropped"]}
json.dump(snap, open(f"{d}/snap.json", "w"))
print(f"flywheel_fleet_smoke: capture OK (shards per member {per}, "
      f"{captured} captured, dup manifests dropped "
      f"{merged['duplicates_dropped']})")
EOF

# ---- act 3: distributed mine -> replay train -> gated promotion ----------
echo "flywheel_fleet_smoke: [3/4] fleet round: merge/fold -> train -> gate"
python flywheel.py fleet --capture-dir "$cap" --top-k 16 \
  --min-label-score 0.0 --eval-every 4 --quality-slack 1.0 \
  --ckpt-prefix "$ckpt" --promote-to "127.0.0.1:$RP" --rounds 2 \
  --telemetry-dir "$dir/tel_fleet_driver" -- \
  python train_end2end.py --network resnet50 --synthetic \
  --synthetic_images 16 \
  --cfg "tpu__SCALES=((64,96),)" --cfg "tpu__MAX_GT=4" \
  --cfg "network__ANCHOR_SCALES=(2,4)" \
  --cfg "TRAIN__RPN_PRE_NMS_TOP_N=200" \
  --cfg "TRAIN__RPN_POST_NMS_TOP_N=32" \
  --cfg "TRAIN__BATCH_ROIS=16" \
  --prefix "$ckpt" --end_epoch 1 --num-steps 6 --frequent 2 \
  --replay-ratio 0.5 --replay-thresh 0.0 \
  | tee "$dir/fleet.json"

# ---- act 4: the promoted generation is live on EVERY member --------------
echo "flywheel_fleet_smoke: [4/4] certify fleet-wide promotion"
python - "$RP" "$dir" <<'EOF'
import json, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, d = int(sys.argv[1]), sys.argv[2]
snap = json.load(open(f"{d}/snap.json"))
fleet = json.loads(open(f"{d}/fleet.json").read().strip().splitlines()[-1])
assert fleet["promoted"] >= 1, f"fleet loop never promoted: {fleet}"
assert fleet["mined"] > 0 and fleet["eval"] is not None, fleet
assert sorted(fleet["members"]) == ["m0", "m1"], fleet
assert fleet["duplicates_dropped"] >= 1, fleet
deadline = time.time() + 120
while True:
    status, m = tcp_http_request("127.0.0.1", port, "GET", "/metrics",
                                 timeout=10)
    assert status == 200, m
    fab = m["fabric"]
    gens = [r["generation"] for r in fab["members"].values()]
    if (fab["generation"] > snap["generation_before"] and len(gens) == 2
            and all(g == fab["generation"] for g in gens)
            and fab["ready"] == 2):
        break
    if time.time() > deadline:
        sys.exit(f"promoted generation never rolled fleet-wide: {fab}")
    time.sleep(1)
c = fab["counters"]
assert c["reload_rollback"] == 0, c
assert c["quality_rejected"] == 0, c
snap["generation_after"] = fab["generation"]
snap["mined"] = fleet["mined"]
snap["scanned"] = fleet["scanned"]
snap["promoted"] = fleet["promoted"]
snap["drift"] = fleet.get("drift") or {}
json.dump(snap, open(f"{d}/snap.json", "w"))
print(f"flywheel_fleet_smoke: promotion OK (generation "
      f"{snap['generation_before']} -> {snap['generation_after']} on "
      f"every member, reloads={c['reload']})")
EOF

# the freshly-promoted fleet still serves clean
python scripts/loadgen.py --port "$RP" --n 6 --rate 10 \
  --short 80 --long 110 --assert-2xx >/dev/null
kill -TERM "$m0pid" "$m1pid" "$rpid"
wait "$rpid" || true
wait "$m0pid" "$m1pid" || true
trap - EXIT

# ---- report + perf gate --------------------------------------------------
python - "$dir" <<'EOF'
import json, sys
d = sys.argv[1]
snap = json.load(open(f"{d}/snap.json"))
doc = {
    "schema": "mxr_flywheel_report", "version": 1,
    "captured": snap["captured"],
    "mined": snap["mined"],
    "scanned": snap["scanned"],
    "generation_before": snap["generation_before"],
    "generation_after": snap["generation_after"],
    # fleet-mode ADDITIVE fields (FLYWHEEL_r02+): generation_promoted
    # is the chaos-certification floor scripts/perf_gate.py gates on
    "members": 2,
    "generation_promoted": snap["promoted"],
    "promotion_gate_pass": snap["promoted"],
    "drift_detected": 1 if snap["drift"].get("drifted") else 0,
    "duplicates_dropped": snap["duplicates_dropped"],
}
with open(f"{d}/FLYWHEEL_r02.json", "w") as f:
    json.dump(doc, f, indent=1, sort_keys=True)
print(f"flywheel_fleet_smoke: report OK (mined {doc['mined']}/"
      f"{doc['captured']} captured across {doc['members']} members, "
      f"{doc['generation_promoted']} generation(s) promoted)")
EOF
python scripts/perf_gate.py --check-format "$dir"/FLYWHEEL_r*.json
python scripts/perf_gate.py --dir "$dir"

# the fleet driver's telemetry stream renders the flywheel table with
# the fleet counters
python scripts/telemetry_report.py "$dir/tel_fleet_driver" \
  | tee "$dir/report.txt"
grep -E '^flywheel/promoted +[1-9]' "$dir/report.txt"
echo "flywheel_fleet_smoke: OK"
