#!/usr/bin/env bash
# Telemetry smoke: 2-step synthetic train with --telemetry-dir on, then fold
# the JSONL stream into the human table and BENCH-compatible rows.
# Second pass: the same run with --loader-workers 2 — the multi-worker host
# pipeline must emit its pool instrumentation (loader/assembly_wait,
# loader/worker_busy, per-worker produce spans) and must not blow up
# train/loader_wait vs the serial producer on the same fixture.
set -e
dir=${TELEMETRY_DIR:-/tmp/mxr_telemetry_smoke}
rm -rf "$dir"
python train_end2end.py --network resnet50 --synthetic --synthetic_images 8 \
  --prefix /tmp/mxr_tel_smoke_ckpt --end_epoch 1 --num-steps 2 --frequent 1 \
  --telemetry-dir "$dir" "$@"
test -f "$dir/events_rank0.jsonl"
test -f "$dir/summary.json"
python scripts/telemetry_report.py "$dir"
python scripts/telemetry_report.py "$dir" --bench

wdir=${TELEMETRY_DIR:-/tmp/mxr_telemetry_smoke}_workers
rm -rf "$wdir"
python train_end2end.py --network resnet50 --synthetic --synthetic_images 8 \
  --prefix /tmp/mxr_tel_smoke_ckpt_w --end_epoch 1 --num-steps 2 --frequent 1 \
  --loader-workers 2 --telemetry-dir "$wdir" "$@"
test -f "$wdir/events_rank0.jsonl"
python scripts/telemetry_report.py "$wdir"
python - "$dir" "$wdir" <<'EOF'
import json, sys

serial_dir, worker_dir = sys.argv[1], sys.argv[2]
with open(f"{serial_dir}/summary.json") as f:
    serial = json.load(f)
with open(f"{worker_dir}/summary.json") as f:
    workers = json.load(f)

# the pool's own instrumentation must be in the stream
for span in ("loader/assembly_wait", "loader/worker0/produce",
             "loader/worker1/produce"):
    assert span in workers["spans"], f"missing pool span {span}"
assert "loader/worker_busy" in workers["gauges"], "missing worker_busy gauge"

# loader_wait must not regress catastrophically vs serial: a 2-step smoke
# on a loaded CI box is noisy, so this is a blown-up-pipeline tripwire
# (order-of-magnitude), not a perf assertion — bench.py --mode loader is
# the measured comparison
s = serial["spans"].get("train/loader_wait", {}).get("total_s", 0.0)
w = workers["spans"].get("train/loader_wait", {}).get("total_s", 0.0)
assert w <= 10 * max(s, 0.1) + 2.0, \
    f"loader_wait blew up with workers: {w:.3f}s vs serial {s:.3f}s"
print(f"telemetry_smoke: pool counters present; "
      f"loader_wait workers={w:.3f}s serial={s:.3f}s")
EOF
