#!/usr/bin/env bash
# Telemetry smoke: 2-step synthetic train with --telemetry-dir on, then fold
# the JSONL stream into the human table and BENCH-compatible rows.
set -e
dir=${TELEMETRY_DIR:-/tmp/mxr_telemetry_smoke}
rm -rf "$dir"
python train_end2end.py --network resnet50 --synthetic --synthetic_images 8 \
  --prefix /tmp/mxr_tel_smoke_ckpt --end_epoch 1 --num-steps 2 --frequent 1 \
  --telemetry-dir "$dir" "$@"
test -f "$dir/events_rank0.jsonl"
test -f "$dir/summary.json"
python scripts/telemetry_report.py "$dir"
python scripts/telemetry_report.py "$dir" --bench
