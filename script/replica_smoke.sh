#!/usr/bin/env bash
# Multi-replica serving-plane smoke (CPU-friendly): three acts over the
# real model with synthetic weights, sharing one AOT program cache so
# only the first boot compiles.
#
#   1. Baseline — the classic single-replica server, measured with
#      scripts/loadgen.py for the per-replica imgs/sec reference.
#   2. Chaos — a 2-replica plane where replica 0 SIGKILLs itself
#      mid-burst (MXR_FAULT_REPLICA_KILL_AFTER): every client response
#      must be 200/503 only (transport errors are absorbed by the
#      router's retry-on-alternate), the availability floor must hold,
#      the supervisor must respawn the corpse back to ready=2, and the
#      parent must leave a replica_down flight dump.
#   3. Hot reload — a fresh 2-replica plane with --watch-checkpoints; a
#      REAL CheckpointManager epoch save lands mid-traffic and rolls
#      through both replicas with ZERO non-2xx responses
#      (loadgen --assert-2xx is the zero-dropped-requests gate),
#      generation 1 everywhere, no rollback.  The same plane then takes
#      a burst for the aggregate throughput number.
#
# The baseline/aggregate pair + chaos availability become an
# mxr_replica_report (REPLICA_r01.json) scored by scripts/perf_gate.py
# as absolute-floor rows.
#
#   bash script/replica_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${REPLICA_SMOKE_DIR:-/tmp/mxr_replica_smoke}
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"   # shared AOT warm-start: 3 boots, 1 compile

common=(--network resnet50 --synthetic --serve-batch 2 --max-delay-ms 20
        --max-queue 32 --deadline-ms 120000 --program-cache "$cache"
        --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
        --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

# wait_ready SOCK PID WANT: poll until the server is ready — /readyz for
# the single server (WANT=1), the router's /metrics supervisor.ready
# count for a plane (warmup + compile gate readiness, so this can take a
# while on a cold cache)
wait_ready() {
python - "$1" "$2" "$3" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid, want = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming ready")
    try:
        if want <= 1:
            status, _ = unix_http_request(sock, "GET", "/readyz", timeout=5)
            if status == 200:
                sys.exit(0)
        else:
            status, m = unix_http_request(sock, "GET", "/metrics", timeout=5)
            if status == 200 and m["supervisor"]["ready"] >= want:
                sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became ready")
EOF
}

# ---- act 1: single-replica baseline --------------------------------------
echo "replica_smoke: [1/3] single-replica baseline"
sock1="$dir/single.sock"
python serve.py "${common[@]}" --unix-socket "$sock1" &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_ready "$sock1" "$pid" 1
python scripts/loadgen.py --unix-socket "$sock1" --n 24 --rate 100 \
  --short 80 --long 110 --assert-2xx | tee "$dir/baseline.json"
kill -TERM "$pid"
wait "$pid"
trap - EXIT

# ---- act 2: chaos — kill -9 one of two replicas mid-burst ----------------
echo "replica_smoke: [2/3] chaos: replica 0 SIGKILLs itself mid-burst"
sockc="$dir/chaos.sock"
telc="$dir/tel_chaos"
# replica 0 (and every respawn of it) SIGKILLs itself after serving 6
# requests; rate 2 ≈ what this CPU actually serves, so the queue (and
# the dead-until-probed retry window) stays well inside the retry
# budget and the deadline
MXR_FAULT_REPLICA_KILL_AFTER="0:6" python serve.py "${common[@]}" \
  --replicas 2 --unix-socket "$sockc" --telemetry-dir "$telc" &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_ready "$sockc" "$pid" 2
python scripts/loadgen.py --unix-socket "$sockc" --n 30 --rate 2 \
  --short 80 --long 110 | tee "$dir/chaos.json"

# error budget held during the crash, then the plane healed itself
python - "$dir/chaos.json" "$sockc" "$telc" <<'EOF'
import json, os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
doc = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
bad = set(doc["status"]) - {"200", "503"}
assert not bad, f"chaos burst leaked statuses {sorted(bad)}: {doc['status']}"
assert doc["status"].get("200", 0) >= 24, doc["status"]
assert doc["availability"] >= 0.9, doc
sock, tel = sys.argv[2], sys.argv[3]
deadline = time.time() + 180
while True:  # recovery: the corpse respawned and came back ready
    status, m = unix_http_request(sock, "GET", "/metrics", timeout=10)
    assert status == 200, m
    sup = m["supervisor"]
    if sup["counters"]["respawn"] >= 1 and sup["ready"] == 2:
        break
    if time.time() > deadline:
        sys.exit(f"plane never recovered: {sup}")
    time.sleep(1)
c = sup["counters"]
assert c["transport_error"] + c["retry_ok"] >= 1, \
    f"the kill was never observed on the wire: {c}"
flight = os.path.join(tel, "flight_0.jsonl")
assert os.path.exists(flight), f"no flight dump at {flight}"
assert "replica_down" in open(flight).read(), flight
print(f"replica_smoke: chaos OK (status={doc['status']}, "
      f"availability={doc['availability']}, respawns={c['respawn']}, "
      f"retries={c['retry_ok']}, ttr_s={doc.get('time_to_recover_s')})")
EOF

# post-recovery probe: the healed plane serves clean (4 requests split
# round-robin stay under the respawned replica's next kill_after=6 fuse)
python scripts/loadgen.py --unix-socket "$sockc" --n 4 --rate 10 \
  --short 80 --long 110 --assert-2xx >/dev/null
kill -TERM "$pid"
wait "$pid"
trap - EXIT

# ---- act 3: rolling hot-reload under traffic -----------------------------
echo "replica_smoke: [3/3] zero-downtime rolling reload"
sockr="$dir/reload.sock"
telr="$dir/tel_reload"
ckpt="$dir/ckpt"
stage="$dir/stage"
mkdir -p "$ckpt"
python serve.py "${common[@]}" --replicas 2 --unix-socket "$sockr" \
  --telemetry-dir "$telr" --watch-checkpoints "$ckpt" \
  --watch-interval-s 1 &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT

# build a REAL PR-2 epoch save (denormalize-at-save path) into a staging
# dir while the plane warms up; it is renamed into the watched prefix
# mid-traffic below, exactly how a training run commits a checkpoint
python - "$stage" <<'EOF'
import dataclasses, sys
import jax
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
cfg = generate_config("resnet50", "PascalVOC",
                      TEST__RPN_PRE_NMS_TOP_N=300,
                      TEST__RPN_POST_NMS_TOP_N=32)
cfg = cfg.replace(
    network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4)),
    tpu=dataclasses.replace(cfg.tpu, SCALES=((96, 128),)))
model = build_model(cfg)
params = init_params(model, cfg, jax.random.PRNGKey(1), batch_size=1)
CheckpointManager(sys.argv[1]).save_epoch(1, params, cfg)
print("replica_smoke: epoch-1 checkpoint staged")
EOF

wait_ready "$sockr" "$pid" 2

# steady traffic spanning the whole roll; --assert-2xx IS the
# zero-dropped-requests gate (a draining replica's 503 must be retried
# onto its peer, never surfaced)
python scripts/loadgen.py --unix-socket "$sockr" --n 50 --rate 2 \
  --short 80 --long 110 --assert-2xx >"$dir/reload_traffic.json" &
lg=$!
sleep 2
mv "$stage/1" "$ckpt/1"   # atomic rename = orbax's own commit protocol
wait "$lg"                # any non-2xx during the swap fails the smoke

# generation 1 live on every replica, one reload per replica, no rollback
python - "$sockr" <<'EOF'
import sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock = sys.argv[1]
deadline = time.time() + 120
while True:
    status, m = unix_http_request(sock, "GET", "/metrics", timeout=10)
    assert status == 200, m
    sup = m["supervisor"]
    gens = [r["generation"] for r in sup["replicas"].values()]
    if (sup["generation"] == 1 and len(gens) == 2
            and all(g == 1 for g in gens) and sup["ready"] == 2):
        break
    if time.time() > deadline:
        sys.exit(f"generation 1 never fully rolled: {sup}")
    time.sleep(1)
c = sup["counters"]
assert c["reload"] == 2, c
assert c["reload_rollback"] == 0, c
print(f"replica_smoke: reload OK (generation={sup['generation']}, "
      f"reloads={c['reload']}, rollbacks={c['reload_rollback']})")
EOF

# aggregate throughput of the (freshly reloaded) 2-replica plane, same
# burst shape as the baseline
python scripts/loadgen.py --unix-socket "$sockr" --n 24 --rate 100 \
  --short 80 --long 110 --assert-2xx | tee "$dir/aggregate.json"
kill -TERM "$pid"
wait "$pid"
trap - EXIT

# ---- report + perf gate --------------------------------------------------
python - "$dir" <<'EOF'
import json, sys
d = sys.argv[1]
def last_json(p):
    return json.loads(open(p).read().strip().splitlines()[-1])
base = last_json(f"{d}/baseline.json")
agg = last_json(f"{d}/aggregate.json")
chaos = last_json(f"{d}/chaos.json")
doc = {
    "schema": "mxr_replica_report", "version": 1,
    "replicas": 2,
    "per_replica_imgs_per_sec": base["imgs_per_sec"],
    "aggregate_imgs_per_sec": agg["imgs_per_sec"],
    # CPU smoke: both replicas contend for the same host cores, so
    # near-linear scaling is impossible here — override the 0.85
    # default floor the one-device-group-per-replica TPU gate uses
    "linearity_floor": 0.35,
    "availability": chaos["availability"],
    "availability_floor": 0.9,
    "time_to_recover_s": chaos.get("time_to_recover_s"),
}
with open(f"{d}/REPLICA_r01.json", "w") as f:
    json.dump(doc, f, indent=1, sort_keys=True)
lin = doc["aggregate_imgs_per_sec"] / (2 * doc["per_replica_imgs_per_sec"])
print(f"replica_smoke: report OK (linearity={lin:.2f}, "
      f"availability={doc['availability']})")
EOF
python scripts/perf_gate.py --check-format "$dir"/REPLICA_r*.json
python scripts/perf_gate.py --dir "$dir"
echo "replica_smoke: OK"
