#!/usr/bin/env bash
# Data-flywheel smoke (ISSUE 13, CPU-friendly): the serve→train→serve
# loop end to end through the real CLI drivers.
#
#   1. Serve — a single synthetic-weight server with request capture ON
#      (--capture-dir) and --watch-checkpoints armed on an empty prefix.
#      scripts/loadgen.py drives traffic with --capture-check: the
#      /metrics flywheel captured-delta must match 2xx submits ×
#      sample rate (the silent-capture-loss gate).
#   2. Mine — flywheel.py mine ranks the spilled shards by hardness and
#      writes the mined-<digest>.json manifest.
#   3. Replay train — train_end2end.py --synthetic with
#      --replay-manifest/--replay-ratio mixes the mined captures into a
#      short run that saves a mid-epoch step checkpoint AND the epoch
#      save, directly into the server's watched prefix.
#   4. Reload — the live server's CheckpointWatcher picks the save up on
#      its own; the /metrics generation must advance (canary passed,
#      replay-trained weights serving).
#
# The run emits FLYWHEEL_r01.json (schema mxr_flywheel_report) scored by
# scripts/perf_gate.py floor rows: mined_fraction > 0 and the reload
# generation strictly advanced — loop closure as a property of the
# build.  The serve telemetry stream must render the "flywheel" section
# in scripts/telemetry_report.py.
#
#   bash script/flywheel_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${FLYWHEEL_SMOKE_DIR:-/tmp/mxr_flywheel_smoke}
rm -rf "$dir"
mkdir -p "$dir"
cap="$dir/capture"
ckpt="$dir/ckpt"
tels="$dir/tel_serve"
mkdir -p "$ckpt"

PORT=$(python - <<'EOF'
import socket
s = socket.socket()
s.bind(("127.0.0.1", 0))
print(s.getsockname()[1])
s.close()
EOF
)

# ---- act 1: serve with capture on, watcher armed -------------------------
echo "flywheel_smoke: [1/4] serve with --capture-dir + --capture-check"
python serve.py --network resnet50 --synthetic --port "$PORT" \
  --serve-batch 2 --max-delay-ms 20 --max-queue 32 --deadline-ms 120000 \
  --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)" \
  --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32 \
  --capture-dir "$cap" --capture-shard-records 8 \
  --watch-checkpoints "$ckpt" --watch-interval-s 1 \
  --telemetry-dir "$tels" &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT

python - "$PORT" "$pid" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, pid = int(sys.argv[1]), int(sys.argv[2])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("server exited before becoming ready")
    try:
        status, _ = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                     timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("server never became ready")
EOF

# --capture-check: captured delta must equal the 24 2xx submits at
# sample rate 1 (silent capture loss exits 1 here)
python scripts/loadgen.py --port "$PORT" --n 24 --rate 20 \
  --short 80 --long 110 --assert-2xx --capture-check \
  | tee "$dir/traffic.json"

# snapshot captured count + pre-reload generation for the report
python - "$PORT" "$dir" <<'EOF'
import json, sys
from mx_rcnn_tpu.serve import tcp_http_request
status, m = tcp_http_request("127.0.0.1", int(sys.argv[1]), "GET",
                             "/metrics", timeout=10)
assert status == 200, m
fw = m["flywheel"]
assert fw["captured"] >= 24, fw        # warmup + the loadgen burst
assert fw["shards"] >= 1, fw           # spills already on disk
snap = {"captured": fw["captured"], "generation_before": m["generation"]}
json.dump(snap, open(f"{sys.argv[2]}/snap.json", "w"))
print(f"flywheel_smoke: capture OK ({fw['captured']} captured, "
      f"{fw['shards']} shards, generation={m['generation']})")
EOF

# ---- act 2: mine the shards into a manifest ------------------------------
echo "flywheel_smoke: [2/4] mine hard examples"
python flywheel.py mine --capture-dir "$cap" --top-k 16 \
  --min-label-score 0.0 --telemetry-dir "$dir/tel_mine" \
  | tee "$dir/mine.json"
manifest=$(python - "$dir/mine.json" <<'EOF'
import json, sys
doc = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert doc["mined"] > 0, f"nothing mined: {doc}"
print(doc["manifest"])
EOF
)

# ---- act 3: short replay-mixed training into the watched prefix ----------
echo "flywheel_smoke: [3/4] replay-mixed training -> $ckpt"
python train_end2end.py --network resnet50 --synthetic \
  --synthetic_images 16 \
  --cfg "tpu__SCALES=((64,96),)" --cfg "tpu__MAX_GT=4" \
  --cfg "network__ANCHOR_SCALES=(2,4)" \
  --cfg "TRAIN__RPN_PRE_NMS_TOP_N=200" \
  --cfg "TRAIN__RPN_POST_NMS_TOP_N=32" \
  --cfg "TRAIN__BATCH_ROIS=16" \
  --prefix "$ckpt" --end_epoch 1 --num-steps 6 --frequent 2 \
  --save-every-n-steps 2 \
  --replay-manifest "$manifest" --replay-ratio 0.5 --replay-thresh 0.0 \
  --telemetry-dir "$dir/tel_train"

# ---- act 4: the live server hot-reloads the save on its own --------------
echo "flywheel_smoke: [4/4] watcher-driven hot reload"
python - "$PORT" "$dir" <<'EOF'
import json, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, d = int(sys.argv[1]), sys.argv[2]
snap = json.load(open(f"{d}/snap.json"))
deadline = time.time() + 180
gen, stable = None, 0
while True:
    try:
        status, m = tcp_http_request("127.0.0.1", port, "GET", "/metrics",
                                     timeout=10)
        rstatus, _ = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                      timeout=10)
    except OSError:
        sys.exit("server died during the reload window")
    assert status == 200, m
    # the training run saved several step checkpoints AND the epoch: the
    # watcher may roll more than one reload.  Wait for a generation
    # advance, then for the watcher to go QUIET — ready and generation
    # stable across a window comfortably longer than --watch-interval-s,
    # so the clean-serve probe below can't race a draining swap.
    if m["generation"] > snap["generation_before"] and rstatus == 200 \
            and m["generation"] == gen:
        stable += 1
        if stable >= 8:
            break
    else:
        stable = 0
    gen = m["generation"]
    if time.time() > deadline:
        sys.exit(f"generation never advanced past "
                 f"{snap['generation_before']} and settled: {gen}")
    time.sleep(1)
snap["generation_after"] = m["generation"]
json.dump(snap, open(f"{d}/snap.json", "w"))
print(f"flywheel_smoke: reload OK (generation "
      f"{snap['generation_before']} -> {snap['generation_after']})")
EOF

# the reloaded server still serves clean
python scripts/loadgen.py --port "$PORT" --n 6 --rate 10 \
  --short 80 --long 110 --assert-2xx >/dev/null
kill -TERM "$pid"
wait "$pid" || true
trap - EXIT

# ---- report + perf gate --------------------------------------------------
python - "$dir" <<'EOF'
import json, sys
d = sys.argv[1]
snap = json.load(open(f"{d}/snap.json"))
mine = json.loads(open(f"{d}/mine.json").read().strip().splitlines()[-1])
doc = {
    "schema": "mxr_flywheel_report", "version": 1,
    "captured": snap["captured"],
    "mined": mine["mined"],
    "scanned": mine["scanned"],
    "generation_before": snap["generation_before"],
    "generation_after": snap["generation_after"],
}
with open(f"{d}/FLYWHEEL_r01.json", "w") as f:
    json.dump(doc, f, indent=1, sort_keys=True)
print(f"flywheel_smoke: report OK (mined {doc['mined']}/{doc['captured']} "
      f"captured, generation {doc['generation_before']} -> "
      f"{doc['generation_after']})")
EOF
python scripts/perf_gate.py --check-format "$dir"/FLYWHEEL_r*.json
python scripts/perf_gate.py --dir "$dir"

# the serve telemetry stream renders the flywheel table
python scripts/telemetry_report.py "$tels" | tee "$dir/report.txt"
grep -E '^flywheel/captured +[1-9]' "$dir/report.txt"
grep -E '^flywheel/shards +[1-9]' "$dir/report.txt"
echo "flywheel_smoke: OK"
