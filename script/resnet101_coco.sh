#!/usr/bin/env bash
# ResNet-101 Faster R-CNN end2end on COCO2017 (BASELINE.json headline config).
set -e
python train_end2end.py --network resnet101 --dataset coco \
  --pretrained model/resnet101_imagenet.npz \
  --prefix model/resnet101_coco_e2e --end_epoch 8 --lr 0.001 --lr_step 6 "$@"
python test.py --network resnet101 --dataset coco \
  --prefix model/resnet101_coco_e2e --epoch 8
