#!/usr/bin/env bash
# AOT warm-start smoke (CPU-friendly): boot serve.py TWICE against one
# MXR_PROGRAM_CACHE dir and assert the persistent program cache did its
# job — the first boot cold-compiles every warmup program (aot_miss ==
# warmup_programs, aot_hit == 0), the second boot compiles ZERO programs
# at warmup (aot_hit == warmup_programs, aot_miss == 0: every executable
# loaded from disk) and its cold start (process launch → first 2xx
# predict) drops materially below the first boot's.  The marker-level
# half of this claim is pinned machine-independently by
# tests/test_warmstart.py; the timing bound lives here, outside
# tier-1, where a wall clock is meaningful.
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${AOT_SMOKE_DIR:-/tmp/mxr_aot_smoke}
rm -rf "$dir"
mkdir -p "$dir"
export MXR_PROGRAM_CACHE="$dir/programs"

boot () {  # $1 = tag; writes $dir/<tag>.json {cold_start_s, counters, compile}
  tag=$1
  sock="$dir/$tag.sock"
  t0=$(python -c 'import time; print(repr(time.time()))')
  python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
    --serve-batch 2 --max-delay-ms 50 --max-queue 32 \
    --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)" \
    --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32 &
  pid=$!
  trap 'kill "$pid" 2>/dev/null || true' EXIT

  # cold start = launch → healthz 200 → first /predict 2xx; then capture
  # /metrics (carries the program registry snapshot under "compile")
  python - "$sock" "$pid" "$t0" "$dir/$tag.json" <<'EOF'
import json, os, sys, time
import numpy as np
from mx_rcnn_tpu.serve import encode_image_payload, unix_http_request
sock, pid, t0, out = sys.argv[1], int(sys.argv[2]), float(sys.argv[3]), \
    sys.argv[4]
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, _ = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            break
    except OSError:
        pass
    time.sleep(1)
else:
    sys.exit("serve.py never became healthy")
img = np.random.RandomState(3).randint(0, 255, (80, 110, 3), dtype=np.uint8)
status, resp = unix_http_request(sock, "POST", "/predict",
                                 encode_image_payload(img), timeout=300)
assert status == 200, resp
cold = time.time() - t0
status, m = unix_http_request(sock, "GET", "/metrics", timeout=30)
assert status == 200
assert "compile" in m, "engine /metrics lacks the registry snapshot"
json.dump({"cold_start_s": round(cold, 3), "counters": m["counters"],
           "compile": m["compile"]}, open(out, "w"))
print(f"{os.path.basename(out)}: cold_start_s={cold:.1f} "
      f"aot_hit={m['compile']['counters']['aot_hit']} "
      f"aot_miss={m['compile']['counters']['aot_miss']}")
EOF

  kill -TERM "$pid"
  wait "$pid" || true
  trap - EXIT
}

boot first
boot second

python - "$dir/first.json" "$dir/second.json" <<'EOF'
import json, sys
first = json.load(open(sys.argv[1]))
second = json.load(open(sys.argv[2]))
w1, w2 = (d["counters"]["warmup_programs"] for d in (first, second))
c1, c2 = first["compile"]["counters"], second["compile"]["counters"]

# boot 1: everything cold — each warmup program was a real XLA compile
assert w1 >= 2, first["counters"]
assert c1["aot_miss"] == w1 and c1["aot_hit"] == 0, c1

# boot 2: ZERO warmup compiles — every program loaded from the cache dir
# boot 1 populated (the PR's acceptance bar)
assert w2 == w1, (w1, w2)
assert c2["aot_hit"] == w2 and c2["aot_miss"] == 0, c2

# and the skipped compiles show up where users feel them: cold start
cold1, cold2 = first["cold_start_s"], second["cold_start_s"]
assert cold2 < cold1 * 0.9, \
    f"warm boot {cold2:.1f}s not materially under cold boot {cold1:.1f}s"
print(f"aot smoke ok: {w2} program(s) warm-started from disk, "
      f"cold start {cold1:.1f}s -> {cold2:.1f}s")
EOF
