#!/usr/bin/env bash
# Zero-data smoke: end2end train + eval on the synthetic dataset.
set -e
python train_end2end.py --network resnet50 --synthetic --synthetic_images 16 \
  --prefix /tmp/mxr_smoke --end_epoch 2 --num-steps 4 --frequent 2 "$@"
python test.py --network resnet50 --synthetic --synthetic_images 16 \
  --prefix /tmp/mxr_smoke --epoch 2
