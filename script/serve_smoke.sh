#!/usr/bin/env bash
# Serving smoke (CPU-friendly): serve.py on synthetic weights + tiny
# buckets, 32 mixed-size open-loop requests through scripts/loadgen.py,
# then assert from the telemetry stream that (1) every response was 2xx,
# (2) every XLA compile happened during warmup — zero steady-state
# recompiles, the subsystem's core guarantee — and (3) p99 queue wait
# stayed under the configured request deadline (head-of-line requests in
# partial flushes legitimately wait the full --max-delay-ms, so the
# deadline, not the delay, is the latency bound).
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${SERVE_SMOKE_DIR:-/tmp/mxr_serve_smoke}
# sized for CPU CI: the tiny model serves ~2 imgs/s there, so a 4 req/s
# open-loop burst of 32 builds a real backlog (the batcher runs full
# batches) while staying far inside the deadline; on a real accelerator
# the queue never builds at all
deadline_ms=60000
rm -rf "$dir"
mkdir -p "$dir"
sock="$dir/serve.sock"
tel="$dir/telemetry"

python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
  --serve-batch 2 --max-delay-ms 50 --max-queue 32 \
  --deadline-ms "$deadline_ms" --telemetry-dir "$tel" \
  --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)" \
  --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32 \
  "$@" &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT

# the socket binds only after warmup finishes compiling both buckets
python - "$sock" "$pid" <<'EOF'
import sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid = sys.argv[1], int(sys.argv[2])
import os
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, doc = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became healthy")
EOF

python scripts/loadgen.py --unix-socket "$sock" --n 32 --rate 4 \
  --deadline-ms "$deadline_ms" --short 80 --long 110 --assert-2xx \
  | tee "$dir/loadgen.json"

# parity: an independent process rebuilds the server's exact synthetic
# params (same PRNGKey recipe + cfg) and checks a served response against
# the offline Predictor + shared-postprocess path on the same pixels
python - "$sock" <<'EOF'
import sys
import jax
import numpy as np
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import prepare_image
from mx_rcnn_tpu.eval import Predictor
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         detections_to_records,
                                         per_class_nms)
from mx_rcnn_tpu.serve import encode_image_payload, unix_http_request
from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

sock = sys.argv[1]
cfg = generate_config(
    "resnet50", "PascalVOC", tpu__SCALES=((96, 128),),
    network__ANCHOR_SCALES=(2, 4),
    # --synthetic sets this on the server (config_from_args); the offline
    # replica must normalize pixels identically or scores diverge
    network__PIXEL_STDS=(127.0, 127.0, 127.0),
    TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32)
model = build_model(cfg)
params = denormalize_for_save(
    init_params(model, cfg, jax.random.PRNGKey(0), batch_size=1), cfg)
pred = Predictor(model, params, cfg)
img = np.random.RandomState(3).randint(0, 255, (80, 110, 3), dtype=np.uint8)
status, resp = unix_http_request(sock, "POST", "/predict",
                                 encode_image_payload(img), timeout=300)
assert status == 200, resp
B = 2  # --serve-batch: a lone request is self-padded to the full batch
prepared, im_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
rois, valid, scores, deltas, _ = [
    np.asarray(jax.device_get(x)) for x in pred.predict(
        np.stack([prepared] * B), np.stack([im_info] * B))]
boxes = decode_image_boxes(rois[0], deltas[0], im_info)
expect = detections_to_records(per_class_nms(
    scores[0], boxes, valid[0], cfg.NUM_CLASSES, cfg.TEST.THRESH,
    cfg.TEST.NMS, cfg.TEST.MAX_PER_IMAGE))
got = resp["detections"]
assert len(got) == len(expect), (len(got), len(expect))
for d, e in zip(got, expect):
    assert d["cls"] == e["cls"], (d, e)
    assert abs(d["score"] - e["score"]) < 1e-4, (d, e)
    assert np.allclose(d["bbox"], e["bbox"], atol=1e-2), (d, e)
print(f"parity ok: {len(got)} detection(s) match the offline "
      f"Predictor + shared-postprocess path")
EOF

# backpressure: an all-at-once burst beyond --max-queue must shed load
# as fast 503s (never stall, never 5xx-other); accepted requests still
# finish inside the deadline
python scripts/loadgen.py --unix-socket "$sock" --n 48 --rate 0 \
  --deadline-ms "$deadline_ms" --short 80 --long 110 \
  | tee "$dir/loadgen_burst.json"
python - "$dir/loadgen_burst.json" <<'EOF'
import json, sys
st = json.load(open(sys.argv[1]))["status"]
assert set(st) <= {"200", "503"}, st
assert st.get("200", 0) >= 1 and st.get("503", 0) >= 1, st
print(f"backpressure ok: {st['200']} served, {st['503']} shed as 503")
EOF

kill -TERM "$pid"
wait "$pid"
trap - EXIT
test -f "$tel/summary.json"

python - "$tel" "$deadline_ms" <<'EOF'
import sys
import numpy as np
from mx_rcnn_tpu.telemetry.report import aggregate, load_events
events = load_events([sys.argv[1]])
deadline_s = float(sys.argv[2]) / 1e3
c = aggregate(events)["counters"]
assert c["serve/recompile"] == c["serve/warmup_programs"], \
    f"recompiled after warmup: {c}"
# the burst phase must have shed load; the paced phase must not have
# blown any deadline
assert c.get("serve/rejected", 0) >= 1, c
assert c.get("serve/deadline_exceeded", 0) == 0, c
waits = [e["dur_s"] for e in events
         if e.get("kind") == "span" and e.get("name") == "serve/queue_wait"]
assert waits, "no serve/queue_wait spans in the stream"
p99 = float(np.percentile(waits, 99))
assert p99 <= deadline_s, f"p99 queue_wait {p99:.3f}s > {deadline_s}s deadline"
print(f"serve smoke ok: {c['serve/recompile']} program(s), all from warmup; "
      f"p99 queue_wait {p99 * 1e3:.1f} ms <= {deadline_s * 1e3:.0f} ms")
EOF

python scripts/telemetry_report.py "$tel" | grep -A 8 "serve health"
