#!/usr/bin/env bash
# SLO-layer smoke (CPU-friendly): serve.py on synthetic weights with the
# adaptive controller on (--target-p99-ms far below what the CPU path can
# hold, so the controller is guaranteed to act), a bursty open-loop load
# through scripts/loadgen.py emitting a machine-readable SLO report, then
# assert that (1) /metrics carries the request-latency histogram with a
# nonzero _count plus live controller state, (2) the report parses and
# scores, (3) the controller recorded at least one slo/ decision in the
# telemetry stream, and (4) the perf gate accepts the new row shape.
#
#   bash script/slo_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${SLO_SMOKE_DIR:-/tmp/mxr_slo_smoke}
deadline_ms=60000
rm -rf "$dir"
mkdir -p "$dir"
sock="$dir/serve.sock"
tel="$dir/telemetry"

# target 50 ms: the tiny CPU model takes hundreds of ms per batch, so the
# windowed p99 breaches immediately and the controller must tighten
python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
  --serve-batch 2 --max-delay-ms 50 --max-queue 32 \
  --deadline-ms "$deadline_ms" --telemetry-dir "$tel" \
  --target-p99-ms 50 --slo-interval-ms 200 --slo-window-s 10 \
  --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)" \
  --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32 \
  "$@" &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT

# the socket binds only after warmup finishes compiling both buckets
python - "$sock" "$pid" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid = sys.argv[1], int(sys.argv[2])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, doc = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became healthy")
EOF

# bursty profile: arrivals in groups of 8 at the same 4 req/s average —
# the queue-depth sawtooth the trend estimator exists for.  No
# --assert-2xx here: controller-shed 503s are expected behavior
python scripts/loadgen.py --unix-socket "$sock" --n 32 --rate 4 \
  --scenario bursty --burst 8 --deadline-ms "$deadline_ms" \
  --short 80 --long 110 --report "$dir/SLO_r01.json" \
  | tee "$dir/loadgen.json"

# while the server is still up: JSON /metrics carries live controller
# state and latency quantiles; the Prometheus view carries the histogram
# family with a nonzero count
python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
sock = sys.argv[1]
status, m = unix_http_request(sock, "GET", "/metrics", timeout=30)
assert status == 200, m
ctrl = m["controller"]
assert ctrl["target_p99_ms"] == 50.0 and ctrl["ticks"] >= 1, ctrl
assert m["latency"]["request_time_p99_ms"] > 0, m["latency"]
assert m["policy"], "no per-bucket policy visible"
status, txt = unix_http_request(sock, "GET", "/metrics", timeout=30,
                                headers={"Accept": "text/plain"})
assert status == 200
count = next(int(float(ln.rsplit(" ", 1)[1])) for ln in txt.splitlines()
             if ln.startswith("mxr_serve_request_time_seconds_count"))
assert count >= 1, "request-latency histogram _count is zero"
assert "mxr_serve_request_time_seconds_bucket" in txt
assert "mxr_slo_target_p99_ms" in txt, "controller gauges not exported"
print(f"slo_smoke: /metrics OK (ticks={ctrl['ticks']}, "
      f"decisions={ctrl['decisions']}, hist count={count})")
EOF

kill -TERM "$pid"
wait "$pid"
trap - EXIT
test -f "$tel/summary.json"

# the SLO report parses, scores the bursty scenario, and the controller
# left at least one decision in the telemetry stream
python - "$dir/SLO_r01.json" "$tel" <<'EOF'
import json, sys
from mx_rcnn_tpu.telemetry.report import aggregate, load_events
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "mxr_slo_report" and doc["version"] == 1, doc
rows = {s["name"]: s for s in doc["scenarios"]}
assert "bursty" in rows, rows
b = rows["bursty"]
assert b["requests"] == 32 and b["p99_ms"] is not None, b
agg = aggregate(load_events([sys.argv[2]]))
c = agg["counters"]
assert c.get("slo/decisions", 0) >= 1, \
    f"controller never acted: {sorted(k for k in c if k.startswith('slo/'))}"
assert "serve/request_time" in agg["hists"], sorted(agg["hists"])
print(f"slo_smoke: report OK (bursty p99 {b['p99_ms']} ms, "
      f"{c['slo/decisions']} controller decision(s), "
      f"{c.get('serve/shed', 0)} shed)")
EOF

# the perf gate must accept the new row dialect, and score it
python scripts/perf_gate.py --check-format "$dir"/SLO_r*.json
python scripts/perf_gate.py --dir "$dir"
echo "slo_smoke: OK"
