#!/usr/bin/env bash
# Multi-model serving smoke (CPU-friendly), asserting the --models
# contract end to end on real servers:
#   1. SINGLE-MODEL baseline boot (cold --program-cache): steady loadgen
#      records the single-model imgs/sec the pool is gated against.
#   2. POOL boot (--models a=...,b=... — same network, a digest-changing
#      per-model config override, so the models have disjoint program
#      keys and AOT subtrees): mixed loadgen --models a=0.7,b=0.3 with
#      --assert-2xx (the burst-on-one-model profile included) writes
#      MULTIMODEL_r01.json — aggregate throughput floored at half the
#      single-model baseline, sibling p99 ceilinged while model a
#      bursts.  /metrics must show zero steady-state recompiles PER
#      MODEL (recompiles == warmup_programs for each), live residency
#      gauges for both models, and a pool scheduler that actually
#      interleaved (sched_batches > 0).
#   3. WARM pool boot over the now-populated cache: the ISSUE-15
#      acceptance — aot_hit summed across ALL models equals
#      warmup_programs summed across all models (every program of every
#      model loads from the persistent cache; the second boot compiles
#      nothing).
#   4. scripts/perf_gate.py gates the trajectory including the new
#      MULTIMODEL rows (aggregate-throughput floor, isolation ceiling).
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${MULTIMODEL_SMOKE_DIR:-/tmp/mxr_multimodel_smoke}
deadline_ms=60000
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"
tinycfg=(--cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
         --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)
# model b = same network, one digest-changing override: disjoint program
# keys + AOT subtree (the realistic two-deployments-one-chip shape)
mmflags=(--models a=resnet50,b=resnet50 --model-arg "b:cfg=TEST__NMS=0.31"
         --model-arg a:weight=2)

wait_healthy() {
  python - "$1" "$2" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import unix_http_request
sock, pid = sys.argv[1], int(sys.argv[2])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("serve.py exited before becoming healthy")
    try:
        status, doc = unix_http_request(sock, "GET", "/healthz", timeout=5)
        if status == 200:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("serve.py never became healthy")
EOF
}

stop() {  # pid — TERM and poll until gone (the server is a subshell
  # child, so ``wait`` can't reap it here)
  kill -TERM "$1" 2>/dev/null || true
  for _ in $(seq 1 100); do
    kill -0 "$1" 2>/dev/null || return 0
    sleep 0.2
  done
  kill -KILL "$1" 2>/dev/null || true
}

boot() {  # sock extra-flags... — start serve.py, echo its pid
  sock="$1"; shift
  python serve.py --network resnet50 --synthetic --unix-socket "$sock" \
    --serve-batch 2 --max-delay-ms 50 --max-queue 64 \
    --deadline-ms "$deadline_ms" --program-cache "$cache" \
    "${tinycfg[@]}" "$@" >"$sock.log" 2>&1 &
  echo $!
}

# ---- 1. single-model baseline ------------------------------------------
sock="$dir/single.sock"
pid=$(boot "$sock")
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
python scripts/loadgen.py --unix-socket "$sock" --n 16 --rate 4 \
  --short 90 --long 120 --deadline-ms "$deadline_ms" --assert-2xx \
  | tee "$dir/single.out"
stop "$pid"
base_tput=$(python - "$dir/single.out" <<'EOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip().startswith("{")]
tput = rows[-1].get("imgs_per_sec")
assert isinstance(tput, (int, float)) and tput > 0, rows[-1]
print(tput)
EOF
)

# ---- 2. pool boot: mixed traffic, per-model counters, the report --------
sock="$dir/pool.sock"
pid=$(boot "$sock" "${mmflags[@]}")
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"

# aggregate throughput must hold at least HALF the single-model rate
# (two models share one device; the pool tax must not eat the rest) and
# model b's p99 is ceilinged while model a bursts — generous bound on a
# shared CI box, the property is that the row is wired, not the number
floor=$(python -c "print(round(0.5 * float('$base_tput'), 3))")
python scripts/loadgen.py --unix-socket "$sock" --n 24 --rate 4 \
  --short 90 --long 120 --deadline-ms "$deadline_ms" \
  --models a=0.7,b=0.3 --burst-model a --assert-2xx \
  --throughput-floor "$floor" --p99-ceiling-ms 30000 \
  --report "${MULTIMODEL_OUT:-MULTIMODEL_r01.json}" \
  | tee "$dir/pool.out"

python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200 and m["multimodel"] is True, m.get("multimodel")
for mid in ("a", "b"):
    c = m["models"][mid]["counters"]
    # the per-model zero-steady-state-recompile contract
    assert c["recompiles"] == c["warmup_programs"] == 2, (mid, c)
    assert c["requests"] > 0, (mid, c)
    r = m["residency"]["models"][mid]
    assert r["resident"] == 1 and r["bytes"] > 0, (mid, r)
p = m["pool"]["counters"]
assert p["sched_batches"] > 0, p
assert m["pool"]["batches"]["a"] > 0 and m["pool"]["batches"]["b"] > 0, \
    m["pool"]
print(f"pool metrics ok: 0 steady-state recompiles on both models, "
      f"{p['sched_batches']} pool batches "
      f"({p['sched_switches']} switches), both models resident")
EOF
stop "$pid"

# ---- 3. warm pool boot: AOT across ALL models ---------------------------
sock="$dir/warm.sock"
pid=$(boot "$sock" "${mmflags[@]}")
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_healthy "$sock" "$pid"
python - "$sock" <<'EOF'
import sys
from mx_rcnn_tpu.serve import unix_http_request
status, m = unix_http_request(sys.argv[1], "GET", "/metrics", timeout=30)
assert status == 200
hits = progs = warm = 0
for mid, doc in m["models"].items():
    rc = doc["compile"]["counters"]
    hits += rc["aot_hit"]
    progs += rc["programs"]
    warm += doc["counters"]["warmup_programs"]
    assert rc["aot_hit"] == rc["programs"], (mid, rc)
# the ISSUE-15 acceptance: summed across ALL registered models, the
# second boot loaded every warmed program from the persistent cache
assert hits == warm == progs and hits > 0, (hits, warm, progs)
print(f"aot warm start ok: {hits}/{progs} program(s) across "
      f"{len(m['models'])} models served from the persistent cache")
EOF
stop "$pid"
trap - EXIT

# ---- 4. gate the trajectory including the multimodel rows ---------------
python scripts/perf_gate.py
echo "multimodel smoke ok"
