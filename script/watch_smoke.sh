#!/usr/bin/env bash
# Watchtower smoke (CPU-friendly): the ISSUE-20 alerting plane over a
# real localhost-TCP fabric — one router running --watch with the
# DEFAULT rule pack (+ --trace, so pages carry forensics) and TWO
# standalone members that self-register with --join, sharing one AOT
# program cache so only the first boot compiles.
#
#   0. A bad rule pack must be a clean boot error naming the offending
#      rule — alerting that half-loads is worse than none.
#   1. Clean pass — fleet warms (a cold boot must NOT page member_stale:
#      a member arms only once it has been ready), loadgen drives clean
#      traffic, and --watch-check asserts NOTHING ever fired.  The live
#      /alerts and /history endpoints answer (alert_query.py --live /
#      --history renders them).
#   2. SLO burn — both members restart with an injected 8s /predict
#      delay (MXR_FAULT_NET_DELAY_MS: response-path only, so probes
#      stay healthy and the fleet looks "up" while every request
#      breaches the 2500ms p99 target).  The crash-restart itself must
#      fire-and-resolve member_stale; the delayed traffic must burn the
#      error budget until fabric_p99_burn pages — loadgen's
#      --watch-expect pins both arcs, and the Prometheus exposition
#      must show mxr_alert_state{alertname="fabric_p99_burn"...} 1
#      while the page is live.
#   3. Recovery — traffic stops, so the budget stops burning (no
#      traffic burns no budget) and the alert must RESOLVE on its own;
#      alert_query.py asserts the full pending→firing→resolved arc AND
#      that the firing transition carried tail-sampled trace ids (the
#      alert→trace join the flight dump relies on).
#
# The run lands as an mxr_watch_report (WATCH_r01.json) scored by
# scripts/perf_gate.py: clean_fired/firing_at_end/rule_errors against
# ZERO ceilings, fault_fired/fault_resolved/fault_trace_ids against
# floors of 1.
#
#   bash script/watch_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${WATCH_SMOKE_DIR:-/tmp/mxr_watch_smoke}
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"   # shared AOT warm-start: 4 boots, 1 compile
tel="$dir/tel"

common=(--network resnet50 --synthetic --serve-batch 2 --max-delay-ms 20
        --max-queue 32 --deadline-ms 120000 --program-cache "$cache"
        --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
        --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

# three free localhost ports: router, member 0, member 1
read -r RP M0 M1 <<<"$(python - <<'EOF'
import socket
socks = [socket.socket() for _ in range(3)]
for s in socks:
    s.bind(("127.0.0.1", 0))
print(" ".join(str(s.getsockname()[1]) for s in socks))
for s in socks:
    s.close()
EOF
)"

# wait_fleet PORT PID WANT: poll the router's /readyz until the
# ready-member count reaches WANT (used after boot AND after the
# fault-phase crash-restart)
wait_fleet() {
python - "$1" "$2" "$3" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, pid, want = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("router exited before the fleet settled")
    try:
        _, doc = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                  timeout=5)
        if doc.get("ready_members", 0) >= want:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit(f"fleet never settled at >= {want} ready members")
EOF
}

# prom_scrape OUTFILE: the router's Prometheus exposition, curl or stdlib
prom_scrape() {
curl -sf "http://127.0.0.1:$RP/metrics?format=prom" >"$1" \
  || python - "$RP" "$1" <<'EOF'
import sys
from mx_rcnn_tpu.serve import tcp_http_request_raw
status, raw, _ = tcp_http_request_raw(
    "127.0.0.1", int(sys.argv[1]), "GET", "/metrics?format=prom",
    headers={"Accept": "text/plain"}, timeout=10)
assert status == 200, status
open(sys.argv[2], "wb").write(raw)
EOF
}

# ---- act 0: a bad rule pack is a boot error, not a degraded alerter ------
echo "watch_smoke: [0/4] bad rule pack rejected at boot"
cat >"$dir/bad_rules.json" <<'EOF'
{"version": 1, "rules": [{"name": "bad", "kind": "burn_rate",
 "metric": "m", "fast_window_s": 300, "slow_window_s": 60}]}
EOF
if timeout -k 10 180 python serve.py --network resnet50 --fabric \
     --port "$RP" --alert-rules "$dir/bad_rules.json" \
     2>"$dir/bad_rules.err"; then
  echo "watch_smoke: bad rule pack was ACCEPTED" >&2
  exit 1
fi
grep -q "rule 0" "$dir/bad_rules.err"
echo "watch_smoke: boot refused, error names the rule"

# ---- act 1: fabric up under the default pack, clean traffic fires nothing
echo "watch_smoke: [1/4] clean pass under the default pack"
python serve.py --network resnet50 --fabric --port "$RP" \
  --probe-interval-s 0.5 --telemetry-dir "$tel" \
  --watch --watch-tick-s 0.5 --trace --trace-sample 1.0 &
rpid=$!
mports=("$M0" "$M1")
mpids=()
for i in 0 1; do
  MXR_REPLICA_INDEX=$i python serve.py "${common[@]}" \
    --port "${mports[i]}" --join "127.0.0.1:$RP" &
  mpids[i]=$!
done
trap 'kill "$rpid" "${mpids[@]}" 2>/dev/null || true' EXIT

wait_fleet "$RP" "$rpid" 2

# the cold boot took >> the rule's 5s hold with zero ready members —
# if warming counted as stale, member_stale would have paged already;
# --watch-check (no --watch-expect) asserts the ledger is EMPTY.
# rate stays under the 2-member CPU capacity (~1.3 req/s): clean
# traffic must actually be clean — queueing past the 2500ms p99
# target would legitimately burn the budget
python scripts/loadgen.py --port "$RP" --n 8 --rate 0.5 \
  --assert-2xx --watch-check | tee "$dir/clean.json"

# the live surfaces answer: /alerts (7 default rules, nothing firing)
# and /history (the watchtower's in-process metric ring)
python scripts/alert_query.py --port "$RP" --live | tee "$dir/live.txt"
grep -q "7 rule(s)" "$dir/live.txt"
grep -q "(no alert instances)" "$dir/live.txt"
python scripts/alert_query.py --port "$RP" --history fleet/ready \
  --window 300 | tee "$dir/history_clean.txt"
grep -q "fleet/ready" "$dir/history_clean.txt"
! grep -q -- "— 0 point(s)" "$dir/history_clean.txt"
echo "watch_smoke: clean pass OK (nothing fired, live surfaces answer)"

# ---- act 2: crash-restart the fleet DEGRADED → burn the error budget ----
echo "watch_smoke: [2/4] 8s /predict delay burns the p99 budget"
kill -KILL "${mpids[@]}" 2>/dev/null || true
wait "${mpids[@]}" 2>/dev/null || true
for i in 0 1; do
  MXR_REPLICA_INDEX=$i MXR_FAULT_NET_DELAY_MS="$i:8000" \
    python serve.py "${common[@]}" \
    --port "${mports[i]}" --join "127.0.0.1:$RP" &
  mpids[i]=$!
done
wait_fleet "$RP" "$rpid" 2

# every routed request now takes ~8s against the 2500ms target: the
# fast/slow burn windows fill and fabric_p99_burn must PAGE before the
# run ends; the crash itself must have fired-and-resolved member_stale
python scripts/loadgen.py --port "$RP" --n 80 --rate 2 \
  --watch-check --watch-expect fabric_p99_burn \
  --watch-expect member_stale | tee "$dir/fault.json"

# the page is on the wire: mxr_alert_state exposes it to Prometheus
prom_scrape "$dir/prom.txt"
grep -q '# HELP mxr_alert_state ' "$dir/prom.txt"
grep 'mxr_alert_state{alertname="fabric_p99_burn"' "$dir/prom.txt" \
  | grep -q ' 1$'
echo "watch_smoke: fabric_p99_burn firing (and exported to Prometheus)"

# ---- act 3: traffic stops → budget stops burning → auto-resolve ---------
echo "watch_smoke: [3/4] quiet traffic lets the burn alert resolve"
ok=0
for _ in $(seq 1 60); do
  if python scripts/alert_query.py --telemetry-dir "$tel" \
       --assert fabric_p99_burn=resolved \
       --require-traces fabric_p99_burn >/dev/null 2>&1; then
    ok=1
    break
  fi
  sleep 2
done
if [ "$ok" != 1 ]; then
  python scripts/alert_query.py --telemetry-dir "$tel" --list || true
  python scripts/alert_query.py --telemetry-dir "$tel" \
    --assert fabric_p99_burn=resolved --require-traces fabric_p99_burn
fi
# the forensic surfaces: per-alert timeline + the violation-bit ring
python scripts/alert_query.py --telemetry-dir "$tel" --list
python scripts/alert_query.py --telemetry-dir "$tel" fabric_p99_burn \
  | tee "$dir/timeline.txt"
grep -q "traces=\[" "$dir/timeline.txt"
python scripts/alert_query.py --port "$RP" \
  --history alert/fabric_p99_burn/violation --window 600 \
  | tee "$dir/history_burn.txt"
grep -q "max 1" "$dir/history_burn.txt"
echo "watch_smoke: burn arc resolved, timeline carries trace ids"

# ---- act 4: report + teardown + gate ------------------------------------
echo "watch_smoke: [4/4] mxr_watch_report through the perf gate"
python - "$tel" "$dir/clean.json" "$RP" "$dir/WATCH_r01.json" <<'EOF'
import glob, json, sys
from mx_rcnn_tpu.serve import tcp_http_request
tel, clean_path, rp, out = (sys.argv[1], sys.argv[2], int(sys.argv[3]),
                            sys.argv[4])
recs = []
for path in glob.glob(f"{tel}/alerts_*.jsonl"):
    for line in open(path):
        line = line.strip()
        if line:
            recs.append(json.loads(line))
fired = [r for r in recs if r.get("state") == "firing"]
resolved = [r for r in recs if r.get("state") == "resolved"]
burn = [r for r in fired if r.get("alert") == "fabric_p99_burn"]
assert burn, "fabric_p99_burn never fired"
trace_ids = sorted({t for r in burn for t in r.get("trace_ids") or []})
assert trace_ids, "the burn page carried no trace ids"
clean = json.load(open(clean_path))
clean_fired = len((clean.get("alerts") or {}).get("fired") or [])
status, doc = tcp_http_request("127.0.0.1", rp, "GET", "/alerts",
                               timeout=10)
assert status == 200, status
assert not doc["firing"], f"still firing at end: {doc['firing']}"
c = doc["counters"]
report = {"schema": "mxr_watch_report", "version": 1,
          "clean_fired": clean_fired,
          "firing_at_end": len(doc["firing"]),
          "rule_errors": c["rule_errors"],
          "fault_fired": len(fired),
          "fault_resolved": len(resolved),
          "fault_trace_ids": len(trace_ids),
          "transitions": c["transitions"],
          "rules": doc["rules"], "ticks": doc["ticks"],
          "alerts_fired": sorted({r["alert"] for r in fired})}
json.dump(report, open(out, "w"), indent=1, sort_keys=True)
print(f"watch_smoke: report OK (fired={report['alerts_fired']}, "
      f"trace_ids={len(trace_ids)}, transitions={c['transitions']}, "
      f"rule_errors={c['rule_errors']})")
EOF

kill -TERM "${mpids[@]}" "$rpid"
wait "$rpid" || true
wait "${mpids[@]}" || true
trap - EXIT

# every transition is first-class telemetry: alert_transition meta
# events in the stream, and the firing page dumped the flight ring
python - "$tel" <<'EOF'
import glob, json, sys
events = []
for path in glob.glob(f"{sys.argv[1]}/events_rank*.jsonl"):
    for line in open(path):
        events.append(json.loads(line))
trans = [e for e in events if e.get("kind") == "meta"
         and e.get("name") == "alert_transition"]
states = {(e["fields"]["alert"], e["fields"]["state"]) for e in trans}
for want in (("fabric_p99_burn", "firing"),
             ("fabric_p99_burn", "resolved")):
    assert want in states, (want, sorted(states))
dumps = [e for e in events if e.get("kind") == "meta"
         and e.get("name") == "flight_trigger"
         and e.get("fields", {}).get("reason") == "alert_firing"]
assert dumps, "no alert_firing flight dump in the stream"
assert glob.glob(f"{sys.argv[1]}/flight_*.jsonl"), "no flight file"
print(f"watch_smoke: telemetry OK ({len(trans)} alert_transition "
      f"event(s), {len(dumps)} flight dump(s))")
EOF

# the report table folds the alert ledger in (the "alerts" section)
python scripts/telemetry_report.py "$tel" | tee "$dir/table.txt"
grep -q "fabric_p99_burn" "$dir/table.txt"

# ---- perf gate -----------------------------------------------------------
python scripts/perf_gate.py --check-format "$dir"/WATCH_r*.json
python scripts/perf_gate.py --dir "$dir"
echo "watch_smoke: OK"
