#!/usr/bin/env bash
# Distributed-tracing smoke (CPU-friendly): the ISSUE-16 pipeline over a
# real fabric — one router plus TWO standalone TCP members (real model,
# synthetic weights) with tracing ON, all span streams sharing one
# telemetry dir.
#
#   1. Traffic — scripts/loadgen.py fires a traced burst
#      (--trace-sample 1.0: every request carries a client-minted trace
#      id).  loadgen itself asserts the echo contract (every 2xx
#      response returns the id that was sent) and its --report rows gain
#      the traced / tail_kept counts.
#   2. Metrics — the router's Prometheus exposition must carry the
#      mxr_trace_* families, and its /metrics JSON the trace section.
#   3. Forensics — scripts/trace_query.py --slowest 3 must render
#      multi-hop trees: the router's fabric/route span over the member's
#      frontend/predict and engine/request batch-causality spans, i.e.
#      ONE trace id across ≥3 hop types and ≥2 members.
#   4. Reports — scripts/telemetry_report.py renders the "tracing"
#      counter section and folds the spans into Chrome/Perfetto JSON
#      with cross-hop flow arrows; scripts/perf_gate.py --check-format
#      validates the SLO report with the new trace fields.
#
#   bash script/trace_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${TRACE_SMOKE_DIR:-/tmp/mxr_trace_smoke}
rm -rf "$dir"
mkdir -p "$dir"
tel="$dir/tel"               # events + spans_* + trace_tail_* together
cache="$dir/program_cache"   # shared AOT warm-start: 3 boots, 1 compile

common=(--network resnet50 --synthetic --serve-batch 2 --max-delay-ms 20
        --max-queue 32 --deadline-ms 120000 --program-cache "$cache"
        --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
        --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

# three free localhost ports: router, member 0, member 1
read -r RP M0 M1 <<<"$(python - <<'EOF'
import socket
socks = [socket.socket() for _ in range(3)]
for s in socks:
    s.bind(("127.0.0.1", 0))
print(" ".join(str(s.getsockname()[1]) for s in socks))
for s in socks:
    s.close()
EOF
)"

wait_ready() {
python - "$1" "$2" "$3" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, pid, want = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("server exited before becoming ready")
    try:
        status, doc = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                       timeout=5)
        if want <= 1 and status == 200:
            sys.exit(0)
        if want > 1 and doc.get("ready_members", 0) >= want:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("server never became ready")
EOF
}

# ---- fabric up: router + 2 members, tracing on everywhere ---------------
echo "trace_smoke: [1/4] traced fabric boot + loadgen echo assertion"
python serve.py --network resnet50 --fabric --port "$RP" \
  --probe-interval-s 1 --telemetry-dir "$tel" \
  --trace --trace-dir "$tel" &
rpid=$!
mports=("$M0" "$M1")
mpids=()
for i in 0 1; do
  MXR_REPLICA_INDEX=$i python serve.py "${common[@]}" \
    --port "${mports[i]}" --join "127.0.0.1:$RP" \
    --trace --trace-dir "$tel" &
  mpids[i]=$!
done
trap 'kill "$rpid" "${mpids[@]}" 2>/dev/null || true' EXIT
wait_ready "$RP" "$rpid" 2

# every request client-minted + echo-asserted; the report rows carry
# traced / tail_kept (additive mxr_slo_report fields)
python scripts/loadgen.py --port "$RP" --n 24 --rate 10 \
  --short 80 --long 110 --scenario steady --trace-sample 1.0 \
  --assert-2xx --report "$dir/SLO_r01.json" | tee "$dir/loadgen.json"

python - "$dir/SLO_r01.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "mxr_slo_report", doc
sc = doc["scenarios"][0]
assert sc["traced"] == 24, f"expected every request traced: {sc}"
assert sc.get("tail_kept") is None or sc["tail_kept"] >= 0, sc
print(f"trace_smoke: loadgen OK (traced={sc['traced']}, "
      f"tail_kept={sc.get('tail_kept')})")
EOF

# ---- act 2: mxr_trace_* on the router's metrics surfaces ----------------
echo "trace_smoke: [2/4] mxr_trace_* families on /metrics"
python - "$RP" <<'EOF'
import http.client, sys
from mx_rcnn_tpu.serve import tcp_http_request
port = int(sys.argv[1])
status, m = tcp_http_request("127.0.0.1", port, "GET", "/metrics",
                             timeout=10)
assert status == 200 and m["trace"]["spans_emitted"] > 0, m.get("trace")
conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
conn.request("GET", "/metrics?format=prom")
resp = conn.getresponse()
text = resp.read().decode()
conn.close()
assert resp.status == 200, text[:200]
for fam in ("mxr_trace_spans_emitted_total", "mxr_trace_tail_kept_total"):
    assert fam in text, f"{fam} missing from the Prometheus exposition"
print(f"trace_smoke: metrics OK (router spans_emitted="
      f"{m['trace']['spans_emitted']}, tail_kept={m['trace']['tail_kept']})")
EOF

kill -TERM "${mpids[@]}" "$rpid"
wait "$rpid" || true
wait "${mpids[@]}" || true
trap - EXIT

# ---- act 3: per-trace forensics across the span files -------------------
echo "trace_smoke: [3/4] trace_query --slowest renders multi-hop trees"
python scripts/trace_query.py --telemetry-dir "$tel" --slowest 3 \
  | tee "$dir/trees.txt"
python - "$dir/trees.txt" <<'EOF'
import sys
blob = open(sys.argv[1]).read()
for hop in ("fabric/route", "frontend/predict", "engine/request",
            "engine/dispatch"):
    assert hop in blob, f"hop {hop} missing from the slowest trees"
assert "[router]" in blob, "router hop missing"
assert "[member0]" in blob or "[member1]" in blob, "member hop missing"
assert "batch_rids=" in blob, "batch-causality attrs missing"
print("trace_smoke: forensics OK (cross-hop trees render)")
EOF

# ---- act 4: report + Perfetto + gate format -----------------------------
echo "trace_smoke: [4/4] telemetry report, Perfetto fold, gate format"
python scripts/telemetry_report.py "$tel" --trace "$dir/perfetto.json" \
  | tee "$dir/report.txt"
python - "$dir/report.txt" "$dir/perfetto.json" <<'EOF'
import json, sys
blob = open(sys.argv[1]).read()
assert "tracing" in blob, "no tracing section in the report"
assert "trace/spans_emitted" in blob, "trace counters missing"
doc = json.load(open(sys.argv[2]))
events = doc["traceEvents"]
spans = [e for e in events if e.get("ph") == "X"
         and e.get("args", {}).get("trace")]
assert spans, "no span slices in the Perfetto fold"
assert len({e["pid"] for e in spans}) >= 2, \
    "spans did not fold into per-member process groups"
flows = {e["ph"] for e in events if e.get("ph") in ("s", "t")}
assert flows == {"s", "t"}, f"cross-hop flow arrows missing: {flows}"
print(f"trace_smoke: perfetto OK ({len(spans)} span slices, "
      f"{len({e['pid'] for e in spans})} process groups)")
EOF
python scripts/perf_gate.py --check-format "$dir"/SLO_r*.json
echo "trace_smoke: OK"
