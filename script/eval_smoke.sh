#!/usr/bin/env bash
# Overlapped-eval smoke: a tiny synthetic eval through all three pred_eval
# variants (serial / pipelined / --device-postprocess), proving the whole
# contract end to end on a box with no accelerator:
#   * pipelined detections are BIT-IDENTICAL to the serial loop's
#     (det_cache pickles compared byte-for-byte),
#   * the steady state does not recompile: a second pipelined eval on the
#     same warm registry adds zero programs,
#   * the telemetry stream carries the eval_pipeline meta row and the
#     report renders the "eval pipeline" table,
#   * a bench.py --mode eval row wrapped as a BENCH_r07-shaped artifact
#     passes scripts/perf_gate.py --check-format.
set -e
base=${EVAL_SMOKE_DIR:-/tmp/mxr_eval_smoke}
rm -rf "$base"
mkdir -p "$base"
export MXR_PROGRAM_CACHE="$base/cache"
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

python - "$base" <<'EOF'
import dataclasses, json, pickle, sys

import jax
import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
from mx_rcnn_tpu.eval import Predictor, pred_eval
from mx_rcnn_tpu.models import build_model, init_params

base = sys.argv[1]
cfg = generate_config("resnet50", "PascalVOC",
                      TEST__RPN_PRE_NMS_TOP_N=300,
                      TEST__RPN_POST_NMS_TOP_N=32)
cfg = cfg.replace(
    network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4)),
    tpu=dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8))
ds = SyntheticDataset(num_images=4, height=96, width=128)
roidb = ds.gt_roidb()
model = build_model(cfg)
params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128))
pred = Predictor(model, params, cfg)

telemetry.configure(f"{base}/tel", run_meta={"driver": "eval_smoke"})

def run(tag, **kw):
    pred_eval(pred, TestLoader(roidb, cfg, batch_size=1), ds,
              det_cache=f"{base}/dets_{tag}.pkl", **kw)
    return open(f"{base}/dets_{tag}.pkl", "rb").read()

serial = run("serial", inflight=0)
n_warm = len(pred.registry.snapshot()["programs"])
piped = run("piped", inflight=2)
# pipelined == serial, byte for byte (index-addressed results)
assert piped == serial, "pipelined detections differ from serial"
# zero steady-state recompiles: the pipelined pass reused every program
n_after = len(pred.registry.snapshot()["programs"])
assert n_after == n_warm, (n_warm, n_after)
# device-postprocess parity: same per-class counts, scores within float
# tolerance of the host-NMS path
dev = pickle.loads(run("devpost", inflight=2, device_postprocess=True))
host = pickle.loads(serial)
for k in range(1, ds.num_classes):
    for i in range(ds.num_images):
        h, d = host[k][i], dev[k][i]
        assert len(h) == len(d), (k, i, len(h), len(d))
        if len(h):
            np.testing.assert_allclose(d, h, atol=1e-3)
telemetry.shutdown()
print(f"eval_smoke: pipelined==serial over {ds.num_images} images, "
      f"{n_after} programs (0 steady-state recompiles), devpost parity OK")
EOF

# the stream must fold into the report's "eval pipeline" table with all
# three modes as rows
python scripts/telemetry_report.py "$base/tel" | tee "$base/report.txt"
grep -q "eval pipeline" "$base/report.txt"
grep -q "pipelined+devpost" "$base/report.txt"

# BENCH trajectory shape: wrap a bench-eval-shaped line like the driver
# does and format-check it (incl. the eval sub-dict the gate expands
# into the eval_pipeline_speedup floor row)
python - "$base" <<'EOF'
import json, sys

base = sys.argv[1]
parsed = {"metric": "eval_imgs_per_sec", "value": 1.0, "unit": "imgs/sec",
          "vs_baseline": None, "baseline_recorded": True,
          "method": "pred_eval",
          "eval": {"serial_imgs_per_sec": 0.9, "pipelined_imgs_per_sec": 1.0,
                   "device_post_imgs_per_sec": 1.0,
                   "speedup_vs_serial": 1.11}}
with open(f"{base}/BENCH_r07.json", "w") as f:
    json.dump({"n": 7, "cmd": "bench.py --mode eval (smoke)", "rc": 0,
               "tail": "", "parsed": parsed}, f, indent=1)
EOF
python scripts/perf_gate.py --check-format "$base/BENCH_r07.json"

echo "eval_smoke: OK"
