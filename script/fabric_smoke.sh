#!/usr/bin/env bash
# Cross-host serving-fabric smoke (CPU-friendly): the ISSUE-12 topology
# over the real model with synthetic weights — one fabric router plus
# THREE standalone TCP members that self-register with --join — all on
# localhost, sharing one AOT program cache so only the first boot
# compiles.
#
#   1. Baseline — a classic single server over TCP, measured with
#      scripts/loadgen.py for the per-member imgs/sec reference.
#   2. Chaos — kill -9 one of the three members mid-burst.  The router
#      has NO respawn authority over a remote host, so the contract is
#      different from replica_smoke: every client response must be
#      200/503 only (the corpse's connection-refused is absorbed by
#      retry-on-alternate), the availability floor must hold, the pool
#      must EVICT the corpse, and — because the router runs with
#      --partition-floor 0.9 — losing 1/3 of the pool declares a
#      fabric_partition flight dump while the reachable subset keeps
#      serving.  Restarting the member on the same address must be
#      re-admitted by the probe loop alone, healing the partition.
#   3. Hot reload — a REAL CheckpointManager epoch save lands in the
#      router's --watch-checkpoints prefix mid-traffic and rolls
#      through all three REMOTE members with ZERO non-2xx responses
#      (loadgen --assert-2xx is the zero-dropped-requests gate),
#      generation 1 everywhere, no rollback.  The healed fabric then
#      takes a burst under loadgen --fabric for the aggregate
#      throughput number and the per-member request share.
#
# The baseline/aggregate pair + chaos availability become an
# mxr_fabric_report (FABRIC_r01.json) scored by scripts/perf_gate.py as
# absolute-floor rows, and the router's telemetry stream must render a
# "fabric health" section in scripts/telemetry_report.py.
#
#   bash script/fabric_smoke.sh
set -e
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
dir=${FABRIC_SMOKE_DIR:-/tmp/mxr_fabric_smoke}
rm -rf "$dir"
mkdir -p "$dir"
cache="$dir/program_cache"   # shared AOT warm-start: 5 boots, 1 compile

common=(--network resnet50 --synthetic --serve-batch 2 --max-delay-ms 20
        --max-queue 32 --deadline-ms 120000 --program-cache "$cache"
        --cfg "tpu__SCALES=((96,128),)" --cfg "network__ANCHOR_SCALES=(2,4)"
        --cfg TEST__RPN_PRE_NMS_TOP_N=300 --cfg TEST__RPN_POST_NMS_TOP_N=32)

# five free localhost ports: router, baseline, member 0..2
read -r RP BP M0 M1 M2 <<<"$(python - <<'EOF'
import socket
socks = [socket.socket() for _ in range(5)]
for s in socks:
    s.bind(("127.0.0.1", 0))
print(" ".join(str(s.getsockname()[1]) for s in socks))
for s in socks:
    s.close()
EOF
)"

# wait_ready PORT PID WANT: poll the server's /readyz until it reports
# ready — a plain engine /readyz for WANT=1, the fabric router's
# ready_members count otherwise (members warm up + compile behind it,
# so this can take a while on a cold cache)
wait_ready() {
python - "$1" "$2" "$3" <<'EOF'
import os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port, pid, want = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
for _ in range(300):
    try:
        os.kill(pid, 0)
    except OSError:
        sys.exit("server exited before becoming ready")
    try:
        status, doc = tcp_http_request("127.0.0.1", port, "GET", "/readyz",
                                       timeout=5)
        if want <= 1 and status == 200:
            sys.exit(0)
        if want > 1 and doc.get("ready_members", 0) >= want:
            sys.exit(0)
    except OSError:
        pass
    time.sleep(1)
sys.exit("server never became ready")
EOF
}

# ---- act 1: single-server baseline ---------------------------------------
echo "fabric_smoke: [1/3] single-server baseline"
python serve.py "${common[@]}" --port "$BP" &
pid=$!
trap 'kill "$pid" 2>/dev/null || true' EXIT
wait_ready "$BP" "$pid" 1
python scripts/loadgen.py --port "$BP" --n 24 --rate 100 \
  --short 80 --long 110 --assert-2xx | tee "$dir/baseline.json"
kill -TERM "$pid"
wait "$pid"
trap - EXIT

# ---- fabric up: router + 3 self-registering TCP members ------------------
echo "fabric_smoke: [2/3] chaos: kill -9 a member mid-burst"
telf="$dir/tel_fabric"
ckpt="$dir/ckpt"
stage="$dir/stage"
mkdir -p "$ckpt"
# partition floor 0.9: losing ANY of the three members (ready fraction
# 2/3) must declare a partition — the smoke's partition probe and the
# chaos act are the same event
python serve.py --network resnet50 --fabric --port "$RP" \
  --probe-interval-s 1 --partition-floor 0.9 --telemetry-dir "$telf" \
  --watch-checkpoints "$ckpt" --watch-interval-s 1 &
rpid=$!
mports=("$M0" "$M1" "$M2")
mpids=()
for i in 0 1 2; do
  MXR_REPLICA_INDEX=$i python serve.py "${common[@]}" \
    --port "${mports[i]}" --join "127.0.0.1:$RP" &
  mpids[i]=$!
done
trap 'kill "$rpid" "${mpids[@]}" 2>/dev/null || true' EXIT

# stage a REAL PR-2 epoch save for act 3 while the fabric warms up; it
# is renamed into the watched prefix mid-traffic below, exactly how a
# training run commits a checkpoint
python - "$stage" <<'EOF'
import dataclasses, sys
import jax
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
cfg = generate_config("resnet50", "PascalVOC",
                      TEST__RPN_PRE_NMS_TOP_N=300,
                      TEST__RPN_POST_NMS_TOP_N=32)
cfg = cfg.replace(
    network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4)),
    tpu=dataclasses.replace(cfg.tpu, SCALES=((96, 128),)))
model = build_model(cfg)
params = init_params(model, cfg, jax.random.PRNGKey(1), batch_size=1)
CheckpointManager(sys.argv[1]).save_epoch(1, params, cfg)
print("fabric_smoke: epoch-1 checkpoint staged")
EOF

wait_ready "$RP" "$rpid" 3

# ---- act 2: chaos burst --------------------------------------------------
# rate 2 ≈ what this CPU serves; the 1s probe interval leaves the corpse
# routable long enough that requests land on it and exercise the
# retry-on-alternate path
python scripts/loadgen.py --port "$RP" --n 30 --rate 2 \
  --short 80 --long 110 >"$dir/chaos.json" &
lg=$!
sleep 3
kill -9 "${mpids[0]}"
wait "$lg"
tail -n 1 "$dir/chaos.json"

# error budget held during the kill, the corpse was evicted, and the
# sub-floor ready fraction was declared a partition (flight dump)
python - "$dir/chaos.json" "$RP" "$telf" <<'EOF'
import json, os, sys, time
from mx_rcnn_tpu.serve import tcp_http_request
doc = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
bad = set(doc["status"]) - {"200", "503"}
assert not bad, f"chaos burst leaked statuses {sorted(bad)}: {doc['status']}"
assert doc["status"].get("200", 0) >= 24, doc["status"]
assert doc["availability"] >= 0.9, doc
port, tel = int(sys.argv[2]), sys.argv[3]
deadline = time.time() + 120
while True:  # the pool noticed: eviction + partition declared
    status, m = tcp_http_request("127.0.0.1", port, "GET", "/metrics",
                                 timeout=10)
    assert status == 200, m
    c = m["fabric"]["counters"]
    if c["member_evicted"] >= 1 and c["partition"] >= 1:
        break
    if time.time() > deadline:
        sys.exit(f"eviction/partition never declared: {c}")
    time.sleep(1)
assert c["transport_error"] + c["retry_ok"] >= 1, \
    f"the kill was never observed on the wire: {c}"
flight = os.path.join(tel, "flight_0.jsonl")
assert os.path.exists(flight), f"no flight dump at {flight}"
blob = open(flight).read()
assert "member_evicted" in blob, flight
assert "fabric_partition" in blob, flight
print(f"fabric_smoke: chaos OK (status={doc['status']}, "
      f"availability={doc['availability']}, evictions="
      f"{c['member_evicted']}, retries={c['retry_ok']}, "
      f"ttr_s={doc.get('time_to_recover_s')})")
EOF

# re-admission: restart the member on the SAME address — the router's
# re-probe loop alone must bring it back and heal the partition
MXR_REPLICA_INDEX=0 python serve.py "${common[@]}" --port "$M0" \
  --join "127.0.0.1:$RP" &
mpids[0]=$!
trap 'kill "$rpid" "${mpids[@]}" 2>/dev/null || true' EXIT
wait_ready "$RP" "$rpid" 3
python - "$RP" <<'EOF'
import sys
from mx_rcnn_tpu.serve import tcp_http_request
status, doc = tcp_http_request("127.0.0.1", int(sys.argv[1]), "GET",
                               "/readyz", timeout=10)
assert status == 200 and not doc["partition"], doc
status, m = tcp_http_request("127.0.0.1", int(sys.argv[1]), "GET",
                             "/metrics", timeout=10)
assert m["fabric"]["counters"]["member_joined"] >= 4, m["fabric"]["counters"]
print("fabric_smoke: re-admission OK (partition healed, "
      f"joins={m['fabric']['counters']['member_joined']})")
EOF

# post-recovery probe: the healed fabric serves clean
python scripts/loadgen.py --port "$RP" --n 6 --rate 10 \
  --short 80 --long 110 --assert-2xx >/dev/null

# ---- act 3: rolling hot-reload under traffic -----------------------------
echo "fabric_smoke: [3/3] zero-downtime rolling reload across the fabric"
# steady traffic spanning the whole roll; --assert-2xx IS the
# zero-dropped-requests gate (a draining member's 503 must be retried
# onto a peer, never surfaced)
python scripts/loadgen.py --port "$RP" --n 50 --rate 2 \
  --short 80 --long 110 --assert-2xx >"$dir/reload_traffic.json" &
lg=$!
sleep 2
mv "$stage/1" "$ckpt/1"   # atomic rename = orbax's own commit protocol
wait "$lg"                # any non-2xx during the swap fails the smoke

# generation 1 live on every remote member, one reload each, no rollback
python - "$RP" <<'EOF'
import sys, time
from mx_rcnn_tpu.serve import tcp_http_request
port = int(sys.argv[1])
deadline = time.time() + 120
while True:
    status, m = tcp_http_request("127.0.0.1", port, "GET", "/metrics",
                                 timeout=10)
    assert status == 200, m
    fab = m["fabric"]
    gens = [r["generation"] for r in fab["members"].values()]
    if (fab["generation"] == 1 and len(gens) == 3
            and all(g == 1 for g in gens) and fab["ready"] == 3):
        break
    if time.time() > deadline:
        sys.exit(f"generation 1 never fully rolled: {fab}")
    time.sleep(1)
c = fab["counters"]
assert c["reload"] == 3, c
assert c["reload_rollback"] == 0, c
print(f"fabric_smoke: reload OK (generation={fab['generation']}, "
      f"reloads={c['reload']}, rollbacks={c['reload_rollback']})")
EOF

# aggregate throughput + per-member request share of the healed,
# freshly-reloaded 3-member fabric (loadgen --fabric reads the router's
# per-member request counters around the burst)
python scripts/loadgen.py --port "$RP" --fabric --n 24 --rate 100 \
  --short 80 --long 110 --assert-2xx | tee "$dir/aggregate.json"
kill -TERM "${mpids[@]}"
kill -TERM "$rpid"
wait "$rpid" || true
wait "${mpids[@]}" || true
trap - EXIT

# the router's telemetry stream renders the fabric health table
python scripts/telemetry_report.py "$telf" | tee "$dir/report.txt"
python - "$dir/report.txt" "$dir/aggregate.json" <<'EOF'
import json, sys
blob = open(sys.argv[1]).read()
assert "fabric health" in blob, "no fabric health section in the report"
for name in ("fabric/member_evicted", "fabric/partition",
             "fabric/reload", "fabric/retry"):
    assert name in blob, f"{name} missing from the fabric health table"
agg = json.loads(open(sys.argv[2]).read().strip().splitlines()[-1])
share = agg.get("member_share") or {}
assert len(share) == 3, share
assert all(v > 0 for v in share.values()), \
    f"a member took no traffic in the aggregate burst: {share}"
print(f"fabric_smoke: report OK (member_share={share})")
EOF

# ---- report + perf gate --------------------------------------------------
python - "$dir" <<'EOF'
import json, sys
d = sys.argv[1]
def last_json(p):
    return json.loads(open(p).read().strip().splitlines()[-1])
base = last_json(f"{d}/baseline.json")
agg = last_json(f"{d}/aggregate.json")
chaos = last_json(f"{d}/chaos.json")
doc = {
    "schema": "mxr_fabric_report", "version": 1,
    "members": 3,
    "per_member_imgs_per_sec": base["imgs_per_sec"],
    "aggregate_imgs_per_sec": agg["imgs_per_sec"],
    # CPU smoke: router + three members contend for the same host
    # cores, so near-linear scaling is impossible here — override the
    # 0.85 default floor the one-host-per-member TPU gate uses
    "linearity_floor": 0.2,
    "availability": chaos["availability"],
    "availability_floor": 0.9,
    # the chaos burst ran under a DECLARED partition (the 0.9 floor
    # makes losing 1/3 of the pool a partition), so its availability is
    # the under-partition number the 0.90 gate scores
    "availability_under_partition": chaos["availability"],
    "time_to_recover_s": chaos.get("time_to_recover_s"),
    "member_share": agg.get("member_share"),
}
with open(f"{d}/FABRIC_r01.json", "w") as f:
    json.dump(doc, f, indent=1, sort_keys=True)
lin = doc["aggregate_imgs_per_sec"] / (3 * doc["per_member_imgs_per_sec"])
print(f"fabric_smoke: report OK (linearity={lin:.2f}, "
      f"availability={doc['availability']})")
EOF
python scripts/perf_gate.py --check-format "$dir"/FABRIC_r*.json
python scripts/perf_gate.py --dir "$dir"
echo "fabric_smoke: OK"
