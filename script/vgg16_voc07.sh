#!/usr/bin/env bash
# Reference recipe parity (script/vgg_voc07.sh): VGG-16 Faster R-CNN end2end.
set -e
python train_end2end.py --network vgg16 --dataset PascalVOC \
  --pretrained model/vgg16_imagenet.npz \
  --prefix model/vgg16_voc07_e2e --end_epoch 10 --lr 0.001 --lr_step 7 "$@"
python test.py --network vgg16 --dataset PascalVOC \
  --prefix model/vgg16_voc07_e2e --epoch 10
