"""Benchmark: training throughput of the flagship config on the attached
TPU chip.

Measures steady-state imgs/sec/chip of the jitted end-to-end train step
(ResNet-101 Faster R-CNN, 608×1024 bucket — the BASELINE.json headline
metric's throughput half; the accuracy half needs COCO on disk).

Prints exactly ONE JSON line:
  {"metric": "train_imgs_per_sec_per_chip", "value": N, "unit": "imgs/sec",
   "vs_baseline": R}

``vs_baseline`` is the ratio against the recorded number in
``BENCH_BASELINE.json`` (the round-1 v5-lite measurement — BASELINE.md's
"first measured baseline of our own"; the reference repo's 8×V100 table was
unrecoverable, see SURVEY §0).  Timing uses chained steps with a single
final sync: on tunneled devices per-step host reads dominate (≫ step time)
and block_until_ready acks early, so only amortized chains measure truth.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_FILE = os.path.join(REPO, "BENCH_BASELINE.json")

BATCH = 1
H, W = 608, 1024
WARMUP = 5
STEPS = 30


def build():
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train import create_train_state, make_train_step

    cfg = generate_config("resnet101", "PascalVOC")
    cfg = cfg.replace(network=dataclasses.replace(
        cfg.network, PIXEL_STDS=(127.0, 127.0, 127.0)))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), BATCH, (H, W))
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=1000)
    step = make_train_step(model, tx, trainable_mask=mask)

    rng = np.random.RandomState(0)
    g = cfg.tpu.MAX_GT
    gtb = np.zeros((BATCH, g, 4), np.float32)
    gtv = np.zeros((BATCH, g), bool)
    gtc = np.zeros((BATCH, g), np.int32)
    for b in range(BATCH):
        for j in range(6):
            x1, y1 = rng.randint(0, W - 200), rng.randint(0, H - 200)
            gtb[b, j] = (x1, y1, x1 + rng.randint(60, 199),
                         y1 + rng.randint(60, 199))
            gtc[b, j] = rng.randint(1, 21)
            gtv[b, j] = True
    images = rng.randn(BATCH, H, W, 3).astype(np.float32)
    if cfg.network.HOST_S2D:  # ship images like the production loader does
        from mx_rcnn_tpu.data.image import space_to_depth2

        images = np.stack([space_to_depth2(im) for im in images])
    batch = dict(
        images=images,
        im_info=np.tile(np.asarray([[H, W, 1.0]], np.float32), (BATCH, 1)),
        gt_boxes=gtb, gt_classes=gtc, gt_valid=gtv,
    )
    return state, step, batch


def main():
    state, step, batch = build()
    # stage the (constant) batch in HBM once: measuring per-step host->device
    # shipping would benchmark the tunnel, not the training step (real
    # training hides it behind the prefetcher's async device_put)
    batch = jax.device_put(batch)
    for i in range(WARMUP):
        state, m = step(state, batch, jax.random.PRNGKey(i))
    jax.block_until_ready(m)
    _ = float(jax.device_get(m["total_loss"]))  # full round-trip fence

    best = None
    for _ in range(4):   # tunnel timing is noisy; best-of-4 chains
        t0 = time.time()
        for i in range(STEPS):
            state, m = step(state, batch, jax.random.PRNGKey(i))
        _ = float(jax.device_get(m["total_loss"]))  # fence via real readback
        dt = (time.time() - t0) / STEPS
        ips = BATCH / dt
        best = ips if best is None else max(best, ips)

    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            base = json.load(f)["value"]
    else:
        base = best
        with open(BASELINE_FILE, "w") as f:
            json.dump({"metric": "train_imgs_per_sec_per_chip", "value": best,
                       "hardware": str(jax.devices()[0]),
                       "config": "resnet101 faster-rcnn end2end 608x1024 b1"},
                      f)

    print(json.dumps({
        "metric": "train_imgs_per_sec_per_chip",
        "value": round(best, 3),
        "unit": "imgs/sec",
        "vs_baseline": round(best / base, 3),
    }))


if __name__ == "__main__":
    main()
