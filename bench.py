"""Benchmark: throughput of the flagship config on the attached TPU chip.

Default (what the driver runs): steady-state imgs/sec/chip of the jitted
end-to-end train step (ResNet-101 Faster R-CNN, 608×1024 bucket — the
BASELINE.json headline metric's throughput half; the accuracy half needs
COCO on disk), printed as exactly ONE JSON line:
  {"metric": "train_imgs_per_sec_per_chip", "value": N, "unit": "imgs/sec",
   "vs_baseline": R}

``vs_baseline`` is the METHOD-CONSISTENT ratio against
``BENCH_BASELINE.json`` (round 5 onward): chain-method runs divide by its
``value_chain`` (the round-4 clean-window chain measurement), staged runs
(``--legacy-dispatch``) by ``value`` (the round-1 v5-lite staged
measurement — BASELINE.md's "first measured baseline of our own"; the
reference repo's 8×V100 table was unrecoverable, see SURVEY §0).  The
emitted ``baseline_method`` field names the denominator's method.
Timing (round 4 onward) uses a ONE-dispatch
``lax.fori_loop`` step chain at two lengths, differenced so the dispatch +
readback fence cancels exactly (`bench_train_chain`) — the async-dispatch
chain it replaces read 23.7–65.9 imgs/s across tunnel windows for a program
whose device step was a stable 12.20 ms; `--legacy-dispatch` keeps the old
method for comparison.

Extra modes (manual, for BASELINE.md's scaling/honesty tables — each also
prints one JSON line):
  python bench.py --batch 4              # chain train step at B=4
  python bench.py --mode loader --loader-workers 4   # HOST pipeline
      standalone: real AnchorLoader over a synthetic roidb (cv2 resize,
      normalize, host s2d, batch assembly) with NO device step and NO
      transfer — pure host-pipeline imgs/sec, the number --loader-workers
      must scale.  method: "host_pipeline", never comparable to device
      rows; the _w{N} metric suffix keys worker counts apart.
  python bench.py --mode train-loader    # loader-INCLUSIVE train: real
      AnchorLoader over a synthetic roidb (cv2 resize, host s2d, prefetch
      thread with on-thread device transfer — all in the measured loop;
      the Speedometer-equivalent number)
  python bench.py --mode infer --batch 4 # chain inference (round 5;
      --legacy-dispatch selects the staged method in BOTH train and
      infer modes; infer output carries a "method" field so ledger rows
      are never cross-method-compared silently)
  python bench.py --mode infer-loader    # TestLoader + im_detect loop incl.
      per-image host decode/readback (the test.py loop without class NMS)
  python bench.py --mode serve --batch 4 # steady-state imgs/sec through the
      REAL ServeEngine (mx_rcnn_tpu/serve): mixed-size raw uint8 requests,
      caller-thread resize, bucket routing, dynamic batching, full
      post-process — everything but HTTP framing.  The gap between this
      and --mode infer is the serving tax (prep + batching + NMS); the
      output's "method" field says "engine" so ledger rows are never
      compared against forward-only numbers silently.
  python bench.py --mode pipeline --auto-tune   # input-pipeline tuner:
      sweep the (k steps/dispatch × loader workers × prefetch [×
      --device-prep]) matrix through the real train hot loop
      (mx_rcnn_tpu/train/pipeline.py), per-cell imgs/s + loader_wait/
      dispatch/fetch_stall/assembly_wait breakdown; --auto-tune persists
      the winner next to the program cache so train_end2end.py
      --tuned-pipeline boots into it.  method: "pipeline"
      (loader-inclusive), its own baseline key ("value_pipeline").
  python bench.py --mode eval            # whole pred_eval loop, three
      variants one row apart: serial (inflight=0), pipelined (the
      overlapped evaluator, the headline) and pipelined +
      --device-postprocess (fused decode+NMS, shrunk readback).  The
      "eval" sub-dict carries all three rates + speedup_vs_serial,
      which scripts/perf_gate.py scores against an absolute 1.0 floor.
      method: "pred_eval", its own baseline key ("value_eval").
  --workers-list/--prefetch-list on --mode loader / train-loader sweep
      the standalone cells in ONE invocation (headline = best, every
      cell in the JSON's "cells" array, metric suffixed _sweep).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import jax
import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_FILE = os.path.join(REPO, "BENCH_BASELINE.json")

# process-start reference for --mode serve's cold_start_s (bench.py is
# the entry script, so import time ≈ process start); the AOT warm-start
# win is exactly the drop in this number between a cold and a warm
# MXR_PROGRAM_CACHE run
_PROC_T0 = time.perf_counter()

H, W = 608, 1024
WARMUP = 5
STEPS = 30
# one-dispatch chain lengths (bench_train_chain); the difference n2-n1 is
# what gets timed, the fixed dispatch+fence cost cancels in the subtraction.
# SIZING MATTERS (first-version bug, r4_tpu_session7.log): with only 30
# steps of difference (~0.4 s device) the tunnel's ±0.1 s+ dispatch-lag
# variance dominated, and taking the BEST of 3 pairs selected favorable
# noise — classic read 113 imgs/s against a 12.35 ms/step device truth
# (chain program profiled by scripts/profile_chain.py; max-of-noisy-
# differences is upward-biased).  160 steps of difference (~2 s device
# classic) bounds the lag noise to a few percent, and the median kills
# the selection bias.
CHAIN_N1, CHAIN_N2 = 40, 200


CFG_OVERRIDES: dict = {}  # set from --cfg (PATH=VALUE, common.py syntax)


def make_cfg(network: str = "resnet101"):
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config(network, "PascalVOC", **CFG_OVERRIDES)
    return cfg.replace(network=dataclasses.replace(
        cfg.network, PIXEL_STDS=(127.0, 127.0, 127.0)))


def synthetic_batch(cfg, batch):
    rng = np.random.RandomState(0)
    g = cfg.tpu.MAX_GT
    gtb = np.zeros((batch, g, 4), np.float32)
    gtv = np.zeros((batch, g), bool)
    gtc = np.zeros((batch, g), np.int32)
    for b in range(batch):
        for j in range(6):
            x1, y1 = rng.randint(0, W - 200), rng.randint(0, H - 200)
            gtb[b, j] = (x1, y1, x1 + rng.randint(60, 199),
                         y1 + rng.randint(60, 199))
            gtc[b, j] = rng.randint(1, 21)
            gtv[b, j] = True
    images = rng.randn(batch, H, W, 3).astype(np.float32)
    if cfg.network.HOST_S2D:  # ship images like the production loader does
        from mx_rcnn_tpu.data.image import space_to_depth2

        images = np.stack([space_to_depth2(im) for im in images])
    out = dict(
        images=images,
        im_info=np.tile(np.asarray([[H, W, 1.0]], np.float32), (batch, 1)),
        gt_boxes=gtb, gt_classes=gtc, gt_valid=gtv,
    )
    if cfg.network.HAS_MASK:
        from mx_rcnn_tpu.data.mask import GT_MASK_SIZE

        out["gt_masks"] = np.ones((batch, g, GT_MASK_SIZE, GT_MASK_SIZE),
                                  np.float32)
    return out


def build(batch: int = 1, network: str = "resnet101", donate: bool = True):
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train import create_train_state, make_train_step

    cfg = make_cfg(network)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), batch, (H, W))
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=1000)
    step = make_train_step(model, tx, trainable_mask=mask, donate=donate)
    return state, step, synthetic_batch(cfg, batch), cfg


def make_chain_fn(step, dbatch, key=None):
    """The ONE definition of the n-step fori_loop chain program (shared
    by `bench_train_chain` and `scripts/profile_chain.py`, whose whole
    purpose is to profile the program the bench times — a drifted copy
    would silently validate a different program).  Per-iteration
    key-derived batch perturbation (sub-pixel gt jitter + epsilon image
    noise) poisons every LICM opportunity downstream; see
    `bench_train_chain` for the measured story."""
    from functools import partial

    if key is None:
        key = jax.random.PRNGKey(0)

    @partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
    def chain(st, n):
        def body(i, s):
            k = jax.random.fold_in(key, i)
            b = dict(dbatch)
            b["images"] = dbatch["images"] + jax.random.uniform(
                k, (), dtype=dbatch["images"].dtype, maxval=1e-3)
            b["gt_boxes"] = dbatch["gt_boxes"] + jax.random.uniform(
                jax.random.fold_in(k, 1), (), dtype=dbatch["gt_boxes"].dtype,
                maxval=0.9)
            return step(s, b, jax.random.fold_in(k, 2))[0]

        return jax.lax.fori_loop(0, n, body, st)

    return chain


def _differenced_rate(run, batch: int, fallback):
    """Shared timing protocol of the chain benches (train + infer): time
    the warmed ``run(n)`` at both CHAIN lengths in 3 pairs, skip pairs a
    window hiccup inverted, and difference so the dispatch + readback
    fence cancels exactly:

        imgs/s = (n2 - n1) * batch / (t(n2) - t(n1))

    Median of 3 valid pairs; LOWER-middle when pairs were skipped — with
    2 samples the upper-middle is max-of-noise, the exact selection bias
    the round-4 rewrite exists to kill (see CHAIN_N note).  ``fallback``
    runs the staged method when every pair inverts (pathological
    window).  ``run(n)`` must block on a real readback before returning.
    """
    n1, n2 = CHAIN_N1, CHAIN_N2
    rates = []
    for _ in range(3):
        ts = {}
        for n in (n1, n2):
            t0 = time.time()
            run(n)
            ts[n] = time.time() - t0
        if ts[n2] > ts[n1]:
            rates.append((n2 - n1) * batch / (ts[n2] - ts[n1]))
    if not rates:
        return fallback()
    return sorted(rates)[(len(rates) - 1) // 2]


def bench_train_chain(batch: int, network: str = "resnet101"):
    """One-dispatch chained-step timing — the headline method since round 4.

    The legacy method (``bench_train_staged``, kept behind
    ``--legacy-dispatch``) dispatches N async step calls and syncs once.
    On a locally-attached host that approaches device-bound throughput,
    but through the axon tunnel each dispatch is an RPC, and in congested
    windows the device starves BETWEEN steps: the same program read
    23.7–65.9 imgs/s across round-3/4 windows while its xplane device
    step was a stable 12.20 ms every time (BASELINE.md round-4 ledger).
    A wall metric whose spread is 3x the quantity it measures is noise.

    Here the whole chain is ONE program: ``lax.fori_loop`` over the train
    step (same jitted step function, traced inline; fresh fold_in key per
    iteration).  The staged batch is PERTURBED with key-derived noise
    every iteration (sub-pixel gt jitter + epsilon image noise) so that
    no data-dependent computation is loop-invariant.  This matters: a
    constant batch let XLA hoist per-batch work — the FPN chain ran
    3.9 ms/step faster than its own per-dispatch device profile because
    the 155k-anchor assign-IoU (constant gt) moved out of the loop, and
    even a 2-batch alternation left the gap (XLA computes both variants
    once and indexes).  Real training recomputes that work per fresh
    batch; the noise forces the loop to as well (r4_tpu_session7.log —
    validated: per-step time in-loop == per-dispatch device profile).
    Transfer overlap for real loaders is separately proven by the
    round-4 loader trace.  Two chain lengths are timed and differenced,
    so the single dispatch + readback fence cancels EXACTLY:

        imgs/s = (n2 - n1) * batch / (t(n2) - t(n1))
    """
    state, step, hbatch, _ = build(batch, network, donate=False)
    chain = make_chain_fn(step, jax.device_put(hbatch))

    n1, n2 = CHAIN_N1, CHAIN_N2
    s0 = int(jax.device_get(state.step))
    box = [state]

    def run(n):
        box[0] = chain(box[0], n)
        return int(jax.device_get(box[0].step))  # readback = fence

    for n in (n1, n2):  # compile + warm both lengths
        s1 = run(n)
    assert s1 - s0 == n1 + n2, f"chain ran {s1 - s0} steps, not {n1 + n2}"
    return _differenced_rate(run, batch,
                             lambda: bench_train_staged(batch, network))


def bench_train_staged(batch: int, network: str = "resnet101"):
    state, step, hbatch, _ = build(batch, network)
    # stage the (constant) batch in HBM once: measuring per-step host->device
    # shipping would benchmark the tunnel, not the training step (real
    # training hides it behind the prefetcher's async device_put)
    dbatch = jax.device_put(hbatch)
    for i in range(WARMUP):
        state, m = step(state, dbatch, jax.random.PRNGKey(i))
    jax.block_until_ready(m)
    _ = float(jax.device_get(m["total_loss"]))  # full round-trip fence

    best = None
    for _ in range(4):   # tunnel timing is noisy; best-of-4 chains
        t0 = time.time()
        for i in range(STEPS):
            state, m = step(state, dbatch, jax.random.PRNGKey(i))
        _ = float(jax.device_get(m["total_loss"]))  # fence via real readback
        dt = (time.time() - t0) / STEPS
        best = max(best or 0.0, batch / dt)
    return best


def _synthetic_roidb(n=48):
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    return SyntheticDataset(num_images=n, height=600, width=800).gt_roidb()


def bench_train_loader(batch: int, network: str = "resnet101",
                       workers: int = 0, prefetch=None):
    """Loader-inclusive: cv2-free synthetic pixels, but the full production
    path otherwise — resize to bucket, host s2d, target padding, prefetch
    thread, host→device transfer ON the prefetch thread (the round-3
    double-buffering ``put`` hook, same as ``fit`` installs: the transfer
    overlaps the previous step instead of landing inside step dispatch),
    one jitted step per loader batch.  Numbers before round 3 (BASELINE.md
    "~50 imgs/s" row) were measured under the old synchronous-transfer
    semantics.

    Best-of-4 fenced epochs, mirroring the staged bench's best-of-4 chains:
    on the tunneled chip, a chain whose steps carry fresh host buffers
    intermittently degrades to ~300 ms/call of transfer handshake (measured;
    the same loop reruns at full speed) — an artifact of the remote-device
    link, not of the loader, so worst-epoch numbers measure the tunnel."""
    from mx_rcnn_tpu.data.loader import AnchorLoader

    state, step, _, cfg = build(batch, network)
    over = {}
    if workers:
        over["LOADER_WORKERS"] = workers
    if prefetch is not None:
        over["PREFETCH"] = int(prefetch)
    if over:
        cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu, **over))
    roidb = _synthetic_roidb()
    loader = AnchorLoader(roidb, cfg, batch, shuffle=True, seed=0)
    loader.put = jax.device_put  # double-buffer: transfer on prefetch thread
    try:
        # warm the jit cache for every bucket the loader can emit
        for b in loader:
            state, m = step(state, b, jax.random.PRNGKey(0))
        jax.block_until_ready(m)

        best = None
        for epoch in range(4):
            imgs = 0
            t0 = time.time()
            for i, b in enumerate(loader):
                state, m = step(state, b, jax.random.PRNGKey(i))
                imgs += batch
            _ = float(jax.device_get(m["total_loss"]))
            best = max(best or 0.0, imgs / (time.time() - t0))
    finally:
        loader.close_workers()
    return best


def bench_host_loader(batch: int, network: str = "resnet101",
                      workers: int = 0, prefetch=None):
    """Host input pipeline STANDALONE: the full AnchorLoader production
    path (cv2 resize to bucket, normalize, flip, host s2d, gt padding,
    batch assembly, prefetch queue) with no device step and no transfer —
    the pure host-side imgs/sec that ``--loader-workers`` exists to scale.
    First epoch is warmup (worker spawn, cv2 caches); best-of-3 after.

    Method-tagged "host_pipeline": this number has no device in it and
    must never land in a ledger row next to device rates."""
    from mx_rcnn_tpu.data.loader import AnchorLoader

    cfg = make_cfg(network)
    over = {}
    if workers:
        over["LOADER_WORKERS"] = workers
    if prefetch is not None:
        over["PREFETCH"] = int(prefetch)
    if over:
        cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu, **over))
    roidb = _synthetic_roidb()
    loader = AnchorLoader(roidb, cfg, batch, shuffle=True, seed=0)
    for _ in loader:  # warmup epoch
        pass
    best = None
    try:
        for _ in range(3):
            imgs = 0
            t0 = time.time()
            for _ in loader:
                imgs += batch
            best = max(best or 0.0, imgs / (time.time() - t0))
    finally:
        loader.close_workers()
    return best


def _parse_int_list(spec) -> list:
    """Comma-separated ints ("0,2,4") → [0, 2, 4]; None/"" → []."""
    if not spec:
        return []
    return [int(tok) for tok in str(spec).split(",") if tok.strip() != ""]


def bench_pipeline(args):
    """Tuned-pipeline sweep (``mx_rcnn_tpu/train/pipeline.py``): drive the
    (k steps/dispatch × loader workers × prefetch depth [× device-prep])
    matrix through the REAL train hot loop — fresh AnchorLoader per cell,
    the same producer-thread put / group-wrap hooks ``fit`` installs, one
    shared step-program cache across cells — and report per-cell imgs/s
    with the loader_wait / dispatch / fetch_stall / assembly_wait
    breakdown.  ``--auto-tune`` persists the winning cell next to the
    program cache so ``train_end2end.py --tuned-pipeline`` boots straight
    into it.  Headline value = best cell's imgs/s."""
    from mx_rcnn_tpu.train.pipeline import (PipelineSweep, parse_cells,
                                            tuned_path)

    cfg = make_cfg(args.network)
    roidb = _synthetic_roidb(args.pipeline_images)
    k_list = _parse_int_list(args.k_list) or [1, 2]
    workers_list = _parse_int_list(args.workers_list) or [0, 2]
    prefetch_list = _parse_int_list(args.prefetch_list) or [2]
    cells = parse_cells(k_list, workers_list, prefetch_list,
                        device_prep=((False, True) if args.device_prep
                                     else (False,)))
    sweep_out = args.sweep_out or os.path.join(
        os.path.dirname(tuned_path()), "pipeline_sweep.jsonl")
    sweep = PipelineSweep(cfg, roidb, batch=args.batch)
    res = sweep.sweep(cells, epochs=args.pipeline_epochs, warmup_epochs=1,
                      auto_tune=args.auto_tune, sweep_jsonl=sweep_out)
    res["sweep_jsonl"] = sweep_out
    return res


def build_infer(batch: int, network: str = "resnet101"):
    from mx_rcnn_tpu.eval.tester import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg = make_cfg(network)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), batch, (H, W))
    params = denormalize_for_save(params, cfg)
    return Predictor(model, params, cfg), cfg


def bench_infer_chain(batch: int, network: str = "resnet101"):
    """One-dispatch chained inference timing (round 5) — the same
    differenced ``lax.fori_loop`` construction as ``bench_train_chain``
    (whose docstring carries the method's full story), applied to the
    ``model.predict`` forward.  Inference has no carried state, so the
    loop carries a f32 sum folded over every output leaf (keeps the body
    alive under DCE); per-iteration epsilon image noise poisons
    loop-invariant hoisting exactly as in the train chain.  Falls back
    to the staged method when every timing pair inverts
    (pathological window)."""
    from functools import partial

    import jax.numpy as jnp

    pred, cfg = build_infer(batch, network)
    hbatch = synthetic_batch(cfg, batch)
    images = jax.device_put(hbatch["images"])
    im_info = jax.device_put(hbatch["im_info"])
    model, params = pred.model, pred.params
    key = jax.random.PRNGKey(0)

    @partial(jax.jit, static_argnames=("n",))
    def chain(n):
        def body(i, acc):
            k = jax.random.fold_in(key, i)
            imgs = images + jax.random.uniform(
                k, (), dtype=images.dtype, maxval=1e-3)
            out = model.apply({"params": params}, imgs, im_info,
                              method=model.predict)
            return acc + sum(jnp.sum(x.astype(jnp.float32))
                             for x in jax.tree.leaves(out))

        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))

    def run(n):
        return float(jax.device_get(chain(n)))  # readback = fence

    for n in (CHAIN_N1, CHAIN_N2):  # compile + warm both lengths
        acc = run(n)
    assert np.isfinite(acc)
    return _differenced_rate(run, batch,
                             lambda: bench_infer_staged(batch, network))


def bench_infer_staged(batch: int, network: str = "resnet101"):
    pred, cfg = build_infer(batch, network)
    hbatch = synthetic_batch(cfg, batch)
    images = jax.device_put(hbatch["images"])
    im_info = jax.device_put(hbatch["im_info"])
    for _ in range(WARMUP):
        out = pred.predict(images, im_info)
    jax.block_until_ready(out)

    best = None
    for _ in range(4):
        t0 = time.time()
        for _ in range(STEPS):
            out = pred.predict(images, im_info)
        _ = float(jax.device_get(out[2]).ravel()[0])  # readback fence
        dt = (time.time() - t0) / STEPS
        best = max(best or 0.0, batch / dt)
    return best


def bench_infer_loader(batch: int, network: str = "resnet101"):
    """The test.py loop: TestLoader (prefetching) + im_detect (device
    forward + full readback + per-image host bbox decode).  Per-class NMS /
    eval excluded — that is pred_eval's accounting, identical in the
    reference."""
    from mx_rcnn_tpu.data.loader import TestLoader
    from mx_rcnn_tpu.eval.tester import im_detect

    pred, cfg = build_infer(batch, network)
    roidb = _synthetic_roidb()
    loader = TestLoader(roidb, cfg, batch_size=batch)
    for b in loader:   # warm all shapes
        im_detect(pred, b)

    best = None
    for _ in range(4):   # best-of-4 epochs (see bench_train_loader note)
        imgs = 0
        t0 = time.time()
        for b in loader:
            dets = im_detect(pred, b)
            imgs += len(dets)
        best = max(best or 0.0, imgs / (time.time() - t0))
    return best


def bench_serve(batch: int, network: str = "resnet101",
                serve_e2e: bool = False, stream: bool = False):
    """Steady-state imgs/sec through the REAL serving engine — the number
    capacity planning needs (how many replicas for X qps), distinct from
    ``--mode infer``'s forward-only rate by exactly the serving tax:
    per-request cv2 resize on submitter threads, bucket routing + batch
    coalescing, device readback, and the shared per-image post-process.

    No HTTP: requests enter at ``ServeEngine.submit`` (what the frontend
    handler calls), so the measurement is transport-independent.  Four
    submitter threads feed mixed-size raw uint8 images — half landscape,
    half portrait, dimensions jittered so every request really pays
    ``resize_to_bucket`` — with per-orientation counts a multiple of
    ``batch`` (steady state runs full batches; partial-flush latency is
    loadgen's department).  503-style rejections are retried with backoff
    exactly like a real client, so backpressure throttles the feeders
    instead of crashing the bench.  Best-of-4 waves after warmup
    (pre-compiles both orientation programs)."""
    import threading

    from mx_rcnn_tpu.eval.tester import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.serve import (RejectedError, ServeEngine, ServeOptions,
                                   warmup)
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg = make_cfg(network)
    model = build_model(cfg)
    # init at the SCALES[0] bucket (init_params' default), not the bench's
    # fixed 608×1024 — serving dispatches bucket programs only
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), batch), cfg)
    pred = Predictor(model, params, cfg)
    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=batch, max_delay_ms=5.0,
        max_queue=max(8 * batch, 16), serve_e2e=serve_e2e)).start()
    t_w = time.perf_counter()
    warmup(engine)
    # warmup's dummy batches run the full submit→serve path, so the end
    # of warmup IS the first-2xx-capable moment: cold_start_s = process
    # start → ready, warmup_compile_s = the compile (or AOT load) share
    warmup_compile_s = time.perf_counter() - t_w
    cold_start_s = time.perf_counter() - _PROC_T0

    short, long_ = (int(s) for s in cfg.tpu.SCALES[0])
    rng = np.random.RandomState(0)
    wave = 8 * batch  # half per orientation → full batches throughout
    imgs = []
    for i in range(wave):
        h, w = (short, long_) if i % 2 == 0 else (long_, short)
        dh, dw = rng.randint(0, 32, 2)
        imgs.append(rng.randint(0, 255, (max(h - dh, 16), max(w - dw, 16), 3),
                                dtype=np.uint8))

    def submit_retry(img):
        while True:
            try:
                return engine.submit(img, deadline_ms=0)
            except RejectedError:
                time.sleep(2e-3)

    feeders = 4
    best = None
    stream_dpf = stream_skip = None
    try:
        for _ in range(4):
            futs = [None] * wave
            t0 = time.time()

            def feed(t):
                for i in range(t, wave, feeders):
                    futs[i] = submit_retry(imgs[i])

            ts = [threading.Thread(target=feed, args=(t,))
                  for t in range(feeders)]
            for th in ts:
                th.start()
            for th in ts:
                th.join()
            for f in futs:
                f.result(timeout=600.0)
            best = max(best or 0.0, wave / (time.time() - t0))
        if stream:
            # streaming phase (--serve-stream): 4 static-motion streams
            # through a StreamManager with the skip gate on — the
            # coalescing/skip wins as counter ratios (dispatches_per_frame,
            # skip_fraction), which perf_gate scores as their OWN series,
            # never against the request/response throughput above
            from mx_rcnn_tpu.serve import StreamManager, StreamOptions

            mgr = StreamManager(engine, StreamOptions(skip_thresh=3.0,
                                                      max_skip=16))
            mgr.warmup()
            n_streams, frames = 4, 32
            rngs = [np.random.RandomState(100 + s)
                    for s in range(n_streams)]
            bases = []
            for s in range(n_streams):
                h, w = (short, long_) if s % 2 == 0 else (long_, short)
                bases.append(rngs[s].randint(0, 255, (h, w, 3),
                                             dtype=np.uint8))
            d0 = engine.counters["dispatches"]

            def run_stream(s):
                for i in range(frames):
                    f = bases[s].copy()
                    # static profile: a handful of ±1 sensor-noise pixels
                    ys = rngs[s].randint(0, f.shape[0], 8)
                    xs = rngs[s].randint(0, f.shape[1], 8)
                    f[ys, xs] = np.clip(
                        f[ys, xs].astype(np.int16) + 1, 0,
                        255).astype(np.uint8)
                    mgr.submit_frame(f"bench-{s}", i + 1,
                                     f).result(timeout=600.0)

            sts = [threading.Thread(target=run_stream, args=(s,))
                   for s in range(n_streams)]
            for th in sts:
                th.start()
            for th in sts:
                th.join()
            total = n_streams * frames
            stream_dpf = round(
                (engine.counters["dispatches"] - d0) / total, 4)
            stream_skip = round(
                mgr.counters["skipped"] / max(mgr.counters["frames"], 1),
                4)
    finally:
        # latency from the engine's own request-time histogram (submit →
        # response, over every timed wave) so the BENCH row carries p50/
        # p99 alongside throughput — "fast but slow-tailed" is visible
        h = engine.hists["serve/request_time"]
        p50, p99 = h.quantile(0.5), h.quantile(0.99)
        # boundary-crossing accounting from the engine's own counters:
        # readback_bytes_per_image is THE fused-path deliverable on a CPU
        # box (the wall-clock win is claimed on TPU), host_prep_ms is the
        # per-request submit-thread prep tax the fusion moves on device
        c = dict(engine.counters)
        readback_per_img = (c.get("readback_bytes", 0)
                            / max(c.get("served", 0), 1))
        host_prep_ms = (c.get("host_prep_ms_total", 0.0)
                        / max(c.get("requests", 0), 1))
        engine.stop()
    return (best,
            (None if p50 is None else round(p50 * 1e3, 3)),
            (None if p99 is None else round(p99 * 1e3, 3)),
            round(cold_start_s, 3), round(warmup_compile_s, 3),
            round(readback_per_img, 1), round(host_prep_ms, 3),
            stream_dpf, stream_skip)


def bench_serve_pool(batch: int, network: str = "resnet101",
                     n_models: int = 2):
    """Aggregate steady-state imgs/sec through a :class:`ModelPool` of
    ``n_models`` same-architecture, independent-weight models — the
    multi-model serving tax in one number.  Same transport-independent
    shape as ``bench_serve`` (submits enter at the engine, no HTTP) but
    requests round-robin across the per-model engines, so the measured
    rate includes cross-model dispatch interleaving and scheduler
    switches.  Gated as its own ``_mmN`` series against the
    single-model ``serve_imgs_per_sec`` floor via MULTIMODEL reports,
    never compared to it directly."""
    import threading

    from mx_rcnn_tpu.eval.tester import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.serve import (ModelPool, RejectedError, ServeEngine,
                                   ServeOptions, warmup)
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg = make_cfg(network)
    model = build_model(cfg)
    pool = ModelPool().start()
    mids = [f"m{i}" for i in range(n_models)]
    t_w = time.perf_counter()
    for i, mid in enumerate(mids):
        params = denormalize_for_save(
            init_params(model, cfg, jax.random.PRNGKey(i), batch), cfg)
        pred = Predictor(model, params, cfg)
        engine = ServeEngine(pred, cfg, ServeOptions(
            batch_size=batch, max_delay_ms=5.0,
            max_queue=max(8 * batch, 16)))
        engine.start(external=True)
        pool.add_model(mid, cfg, pred, engine)
        # warm THIS model before building the next (jax cache-dir order)
        warmup(engine)
    warmup_compile_s = time.perf_counter() - t_w
    cold_start_s = time.perf_counter() - _PROC_T0

    short, long_ = (int(s) for s in cfg.tpu.SCALES[0])
    rng = np.random.RandomState(0)
    # per-model, per-orientation counts stay a multiple of batch so the
    # steady state runs full batches on every engine
    wave = 8 * batch * n_models
    imgs = []
    for i in range(wave):
        h, w = (short, long_) if (i // n_models) % 2 == 0 else (long_, short)
        dh, dw = rng.randint(0, 32, 2)
        imgs.append(rng.randint(0, 255, (max(h - dh, 16), max(w - dw, 16), 3),
                                dtype=np.uint8))

    def submit_retry(i):
        engine = pool.engine_for(mids[i % n_models])
        while True:
            try:
                return engine.submit(imgs[i], deadline_ms=0)
            except RejectedError:
                time.sleep(2e-3)

    feeders = 4
    best = None
    try:
        for _ in range(4):
            futs = [None] * wave
            t0 = time.time()

            def feed(t):
                for i in range(t, wave, feeders):
                    futs[i] = submit_retry(i)

            ts = [threading.Thread(target=feed, args=(t,))
                  for t in range(feeders)]
            for th in ts:
                th.start()
            for th in ts:
                th.join()
            for f in futs:
                f.result(timeout=600.0)
            best = max(best or 0.0, wave / (time.time() - t0))
    finally:
        # worst tenant's tail, not the blended one: max over per-model
        # quantiles — the SLO a pool operator owes EACH model
        p50s, p99s = [], []
        agg = {}
        for mid in mids:
            engine = pool.engine_for(mid)
            h = engine.hists["serve/request_time"]
            q50, q99 = h.quantile(0.5), h.quantile(0.99)
            if q50 is not None:
                p50s.append(q50)
            if q99 is not None:
                p99s.append(q99)
            for k, v in engine.counters.items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        readback_per_img = (agg.get("readback_bytes", 0)
                            / max(agg.get("served", 0), 1))
        host_prep_ms = (agg.get("host_prep_ms_total", 0.0)
                        / max(agg.get("requests", 0), 1))
        sched = dict(pool.counters)
        pool.stop()
    pool_doc = {
        "models": n_models,
        "sched_batches": sched["sched_batches"],
        "sched_switches": sched["sched_switches"],
        "switches_per_batch": round(
            sched["sched_switches"] / max(sched["sched_batches"], 1), 4),
    }
    return (best,
            (round(max(p50s) * 1e3, 3) if p50s else None),
            (round(max(p99s) * 1e3, 3) if p99s else None),
            round(cold_start_s, 3), round(warmup_compile_s, 3),
            round(readback_per_img, 1), round(host_prep_ms, 3), pool_doc)


def bench_serve_cascade(batch: int, network: str = "resnet101",
                        thresh: float = 0.5):
    """Steady-state imgs/sec through a two-model cascade (ISSUE 19):
    every request enters at ``CascadeRouter.submit`` (what the frontend
    calls with --cascade active), answers from the small model unless
    the on-device hardness gate escalates it to the big sibling.  Both
    engines run the fused serve_e2e program — the gate consumes its
    on-device detections.  Same transport-independent shape as
    ``bench_serve``; the measured rate includes the gate dispatch and
    every escalated frame's second (staged-reuse) pass.  Reported as
    ``serve_imgs_per_sec_cascade`` with ``escalation_rate`` alongside —
    its OWN baseline series, never compared to the single-model or
    pool rows (the throughput-vs-big-only floor is loadgen's CASCADE
    report, where both sides run on the same box in the same run)."""
    import threading

    from mx_rcnn_tpu.eval.tester import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.serve import (CascadeRouter, ModelPool, RejectedError,
                                   ServeEngine, ServeOptions, warmup)
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg = make_cfg(network)
    model = build_model(cfg)
    pool = ModelPool().start()
    mids = ("small", "big")
    t_w = time.perf_counter()
    for i, mid in enumerate(mids):
        params = denormalize_for_save(
            init_params(model, cfg, jax.random.PRNGKey(i), batch), cfg)
        pred = Predictor(model, params, cfg)
        engine = ServeEngine(pred, cfg, ServeOptions(
            batch_size=batch, max_delay_ms=5.0,
            max_queue=max(8 * batch, 16), serve_e2e=True))
        engine.start(external=True)
        pool.add_model(mid, cfg, pred, engine)
        warmup(engine)
    cascade = CascadeRouter(pool, "small", "big", thresh=thresh)
    cascade.warmup()  # the gate program compiles before traffic too
    pool.cascade = cascade
    warmup_compile_s = time.perf_counter() - t_w
    cold_start_s = time.perf_counter() - _PROC_T0

    short, long_ = (int(s) for s in cfg.tpu.SCALES[0])
    rng = np.random.RandomState(0)
    wave = 8 * batch
    imgs = []
    for i in range(wave):
        h, w = (short, long_) if i % 2 == 0 else (long_, short)
        dh, dw = rng.randint(0, 32, 2)
        imgs.append(rng.randint(0, 255, (max(h - dh, 16), max(w - dw, 16), 3),
                                dtype=np.uint8))

    def submit_retry(img):
        while True:
            try:
                return cascade.submit(img, deadline_ms=0)
            except RejectedError:
                time.sleep(2e-3)

    feeders = 4
    best = None
    try:
        for _ in range(4):
            futs = [None] * wave
            t0 = time.time()

            def feed(t):
                for i in range(t, wave, feeders):
                    futs[i] = submit_retry(imgs[i])

            ts = [threading.Thread(target=feed, args=(t,))
                  for t in range(feeders)]
            for th in ts:
                th.start()
            for th in ts:
                th.join()
            for f in futs:
                f.result(timeout=600.0)
            best = max(best or 0.0, wave / (time.time() - t0))
    finally:
        # worst engine's tail (the pool convention) + aggregate boundary
        # accounting across both cascade members
        p50s, p99s = [], []
        agg = {}
        for mid in mids:
            engine = pool.engine_for(mid)
            h = engine.hists["serve/request_time"]
            q50, q99 = h.quantile(0.5), h.quantile(0.99)
            if q50 is not None:
                p50s.append(q50)
            if q99 is not None:
                p99s.append(q99)
            for k, v in engine.counters.items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        readback_per_img = (agg.get("readback_bytes", 0)
                            / max(agg.get("served", 0), 1))
        host_prep_ms = (agg.get("host_prep_ms_total", 0.0)
                        / max(agg.get("requests", 0), 1))
        cascade_doc = cascade.metrics()
        pool.stop()
    return (best,
            (round(max(p50s) * 1e3, 3) if p50s else None),
            (round(max(p99s) * 1e3, 3) if p99s else None),
            round(cold_start_s, 3), round(warmup_compile_s, 3),
            round(readback_per_img, 1), round(host_prep_ms, 3),
            cascade_doc)


def bench_infer_mask(batch: int, network: str = "resnet101_fpn_mask"):
    """Full Mask R-CNN eval loop (VERDICT round-2 item 6): pred_eval with
    with_masks=True — forward + per-class NMS + mask chunk drain + 28×28
    paste + RLE encode + segm scoring, over the synthetic imdb.  Times the
    second pred_eval call (first warms every jit shape incl. the mask
    chunks); reports imgs/sec of the WHOLE loop, the number test.py users
    experience."""
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset
    from mx_rcnn_tpu.data.loader import TestLoader
    from mx_rcnn_tpu.eval.tester import pred_eval

    pred, cfg = build_infer(batch, network)
    assert cfg.network.HAS_MASK, f"{network} has no mask head"
    ds = SyntheticDataset(num_images=24, height=600, width=800)
    roidb = ds.gt_roidb()
    pred_eval(pred, TestLoader(roidb, cfg, batch_size=batch), ds,
              with_masks=True)  # warm
    best = None
    for _ in range(2):
        t0 = time.time()
        pred_eval(pred, TestLoader(roidb, cfg, batch_size=batch), ds,
                  with_masks=True)
        best = max(best or 0.0, len(roidb) / (time.time() - t0))
    return best


def bench_eval(batch: int, network: str = "resnet101", num_images: int = 24):
    """Serial vs pipelined vs --device-postprocess through the REAL
    ``pred_eval`` loop over the synthetic imdb — the three eval variants
    one row apart, on the same box, same warm program cache.  Warms every
    jit shape first (incl. the fused device-postprocess program), then
    takes best-of-2 per variant, interleaved so drift hits all three
    equally.  Headline value = pipelined rate; the serial rate is the
    denominator of ``speedup_vs_serial``, which perf_gate scores against
    an absolute floor of 1.0 ("the overlap machinery must not lose to
    the loop it replaced")."""
    from mx_rcnn_tpu.data.loader import TestLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset
    from mx_rcnn_tpu.eval.tester import pred_eval

    pred, cfg = build_infer(batch, network)
    ds = SyntheticDataset(num_images=num_images, height=600, width=800)
    roidb = ds.gt_roidb()

    def run(**kw):
        t0 = time.time()
        pred_eval(pred, TestLoader(roidb, cfg, batch_size=batch), ds,
                  with_masks=cfg.network.HAS_MASK, **kw)
        return len(roidb) / (time.time() - t0)

    run(inflight=2)                             # warm the host-NMS shapes
    run(inflight=2, device_postprocess=True)    # warm the fused program
    rates = {"serial": 0.0, "pipelined": 0.0, "device_post": 0.0}
    for _ in range(2):
        rates["serial"] = max(rates["serial"], run(inflight=0))
        rates["pipelined"] = max(rates["pipelined"], run(inflight=2))
        rates["device_post"] = max(
            rates["device_post"], run(inflight=2, device_postprocess=True))
    return rates


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="train",
                    choices=["train", "loader", "train-loader", "infer",
                             "infer-loader", "infer-mask", "serve",
                             "pipeline", "eval"])
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--loader-workers", type=int, default=0,
                    dest="loader_workers",
                    help="loader/train-loader modes: host input-pipeline "
                         "worker processes (0 = the serial producer); "
                         "non-zero suffixes the metric with _w{N}")
    ap.add_argument("--workers-list", default="", dest="workers_list",
                    help="comma list of worker counts, e.g. 0,2,4 — "
                         "loader/train-loader: sweep standalone cells "
                         "(headline = best, every cell in the JSON); "
                         "pipeline: the matrix's workers axis "
                         "(default 0,2)")
    ap.add_argument("--prefetch-list", default="", dest="prefetch_list",
                    help="comma list of prefetch queue depths — "
                         "loader/train-loader sweep axis / pipeline "
                         "matrix axis (default: config PREFETCH; "
                         "pipeline default 2)")
    ap.add_argument("--k-list", default="", dest="k_list",
                    help="pipeline mode: comma list of steps-per-dispatch "
                         "group sizes (default 1,2)")
    ap.add_argument("--auto-tune", action="store_true", dest="auto_tune",
                    help="pipeline mode: persist the winning cell next to "
                         "the program cache (train_end2end.py/"
                         "train_alternate.py --tuned-pipeline reads it)")
    ap.add_argument("--device-prep", action="store_true", dest="device_prep",
                    help="pipeline mode: sweep device-side preprocessing "
                         "as a matrix axis (each k×w×p cell runs host-prep "
                         "AND device-prep)")
    ap.add_argument("--serve-e2e", action="store_true", dest="serve_e2e",
                    help="serve mode: run the engine with the fused "
                         "single-dispatch serve_e2e program (staged uint8 "
                         "in, (B, cap, 6) detections out).  The metric is "
                         "suffixed _e2e — its own baseline series, never "
                         "compared against the unfused engine rows")
    ap.add_argument("--serve-stream", action="store_true",
                    dest="serve_stream",
                    help="serve mode: also run a streaming phase (4 "
                         "static-motion streams through a StreamManager "
                         "with the frame-delta gate on) and report "
                         "dispatches_per_frame + skip_fraction as their "
                         "own gated series")
    ap.add_argument("--serve-models", type=int, default=0,
                    dest="serve_models",
                    help="serve mode: run N same-architecture, "
                         "independent-weight models behind one ModelPool "
                         "and report AGGREGATE imgs/sec (requests round-"
                         "robin across models).  Metric suffixed _mmN — "
                         "its own series; the JSON carries the pool's "
                         "scheduler counters")
    ap.add_argument("--serve-cascade", action="store_true",
                    dest="serve_cascade",
                    help="serve mode: run a small:big cascade behind a "
                         "CascadeRouter (both engines serve_e2e, the "
                         "on-device hardness gate escalating) and report "
                         "imgs/sec as serve_imgs_per_sec_cascade with "
                         "escalation_rate alongside — its own series, "
                         "never scored against non-cascade rows")
    ap.add_argument("--pipeline-images", type=int, default=32,
                    dest="pipeline_images",
                    help="pipeline mode: synthetic roidb size per epoch")
    ap.add_argument("--pipeline-epochs", type=int, default=1,
                    dest="pipeline_epochs",
                    help="pipeline mode: measured epochs per cell (one "
                         "extra warmup epoch always runs first)")
    ap.add_argument("--sweep-out", default="", dest="sweep_out",
                    help="pipeline mode: per-cell JSONL path (telemetry-"
                         "meta-shaped rows; scripts/telemetry_report.py "
                         "renders the table).  Default: pipeline_sweep."
                         "jsonl next to the program cache")
    ap.add_argument("--network", default=None,
                    help="config preset (e.g. resnet101, resnet101_fpn, "
                         "resnet101_fpn_mask); non-default appears in the "
                         "metric name")
    ap.add_argument("--cfg", action="append", default=[],
                    help="config override PATH=VALUE (python literal; "
                         "common.py syntax), e.g. "
                         "--cfg TRAIN__RPN_ASSIGN_IOU_BF16=True — for "
                         "A/B step-time measurements of ledger levers")
    ap.add_argument("--opt-acc-ab", action="store_true", dest="opt_acc_ab",
                    help="train mode: A/B the optimizer accumulator dtype "
                         "in ONE invocation — the chain bench runs twice "
                         "(TRAIN__OPT_ACC_DTYPE float32 then bfloat16) "
                         "and the JSON carries both rates plus the "
                         "ms/step delta, pinning (or retiring) the "
                         "config.py '−0.26 ms measured' claim.  Headline "
                         "value/baseline compare use the f32 run")
    ap.add_argument("--legacy-dispatch", action="store_true",
                    help="train AND infer modes: use the staged "
                         "async-dispatch method (subject to tunnel "
                         "dispatch-rate noise) instead of the "
                         "one-dispatch fori_loop chain")
    ap.add_argument("--telemetry-dir", default="", dest="telemetry_dir",
                    help="stream the run's telemetry (JSONL events + "
                         "summary JSON) here; the loader/infer-loader/"
                         "infer-mask modes emit the same per-phase spans "
                         "as real training/eval (the instrumented loader "
                         "and tester run inside the measured loop)")
    ap.add_argument("--obs-port", type=int, default=0, dest="obs_port",
                    help="live Prometheus /metrics + /healthz on "
                         "127.0.0.1:PORT while the bench runs "
                         "(telemetry/obs.py; 0 = off)")
    args = ap.parse_args()
    from mx_rcnn_tpu.tools.common import parse_cfg_overrides

    CFG_OVERRIDES.update(parse_cfg_overrides(args.cfg))
    if args.network is None:
        # per-mode default: an explicitly passed network is never rewritten
        args.network = ("resnet101_fpn_mask" if args.mode == "infer-mask"
                        else "resnet101")
    from mx_rcnn_tpu import telemetry
    from mx_rcnn_tpu.tools.common import start_observability

    obs = start_observability(args, "bench",
                              run_meta={"mode": args.mode,
                                        "batch": args.batch,
                                        "network": args.network},
                              configure_telemetry=True)

    tel = telemetry.get()
    t_bench = time.perf_counter()
    infer_method = None
    opt_acc = None
    sweep_cells = None
    pipe = None
    eval_rates = None
    if args.mode == "train":
        fn = bench_train_staged if args.legacy_dispatch else bench_train_chain
        if args.opt_acc_ab:
            ab = {}
            for dt in ("float32", "bfloat16"):
                CFG_OVERRIDES["TRAIN__OPT_ACC_DTYPE"] = dt
                ab[dt] = fn(args.batch, args.network)
            CFG_OVERRIDES.pop("TRAIN__OPT_ACC_DTYPE")
            value = ab["float32"]
            ms = {dt: args.batch / v * 1e3 for dt, v in ab.items()}
            opt_acc = {
                "f32_imgs_per_sec": round(ab["float32"], 3),
                "bf16_imgs_per_sec": round(ab["bfloat16"], 3),
                "f32_ms_per_step": round(ms["float32"], 3),
                "bf16_ms_per_step": round(ms["bfloat16"], 3),
                # positive = bf16 accumulator is faster by this much
                "delta_ms_per_step": round(ms["float32"]
                                           - ms["bfloat16"], 3),
            }
        else:
            value = fn(args.batch, args.network)
        metric = "train_imgs_per_sec_per_chip"
    elif args.mode in ("loader", "train-loader"):
        fn = (bench_host_loader if args.mode == "loader"
              else bench_train_loader)
        metric = ("loader_imgs_per_sec_host" if args.mode == "loader"
                  else "train_imgs_per_sec_loader_inclusive")
        wl = _parse_int_list(args.workers_list)
        pl = _parse_int_list(args.prefetch_list)
        if wl or pl:
            # reproducible standalone sweep: every (workers, prefetch)
            # cell in the JSON, best as the headline.  _sweep keys the
            # metric apart from single-cell rows of the same mode.
            sweep_cells = []
            for w in (wl or [args.loader_workers]):
                for p in (pl or [None]):
                    v = fn(args.batch, args.network, w, p)
                    sweep_cells.append({
                        "workers": w,
                        "prefetch": p,
                        "imgs_per_sec": round(v, 3)})
            value = max(c["imgs_per_sec"] for c in sweep_cells)
            metric += "_sweep"
        else:
            value = fn(args.batch, args.network, args.loader_workers)
            if args.loader_workers:
                metric += f"_w{args.loader_workers}"
        if args.mode == "loader":
            infer_method = "host_pipeline"  # no device in this number:
            # never comparable to device/train/serve rows
    elif args.mode == "pipeline":
        pipe = bench_pipeline(args)
        value = pipe["best"]["imgs_per_sec"]
        metric = "train_imgs_per_sec_pipeline"
        infer_method = "pipeline"  # loader-inclusive real-hot-loop sweep:
        # never comparable to chain/staged dispatch-free rows
    elif args.mode == "infer":
        fn = bench_infer_staged if args.legacy_dispatch else bench_infer_chain
        value = fn(args.batch, args.network)
        metric = "infer_imgs_per_sec"
        # name the method in the artifact: the staged-method BASELINE.md
        # rows share this metric name, and a chain number silently
        # compared against them would cross methods (the train path
        # guards this with value/value_chain + baseline_method)
        infer_method = "staged" if args.legacy_dispatch else "chain"
    elif args.mode == "infer-mask":
        value = bench_infer_mask(args.batch, args.network)
        metric = "infer_imgs_per_sec_mask_eval"
    elif args.mode == "serve":
        serve_pool_doc = None
        serve_cascade_doc = None
        if args.serve_cascade:
            if args.serve_e2e or args.serve_stream or args.serve_models:
                raise SystemExit("--serve-cascade is exclusive with "
                                 "--serve-e2e / --serve-stream / "
                                 "--serve-models")
            (value, serve_p50_ms, serve_p99_ms, serve_cold_start_s,
             serve_warmup_s, serve_readback_b, serve_prep_ms,
             serve_cascade_doc) = bench_serve_cascade(
                 args.batch, args.network)
            serve_stream_dpf = serve_stream_skip = None
            metric = "serve_imgs_per_sec_cascade"
        elif args.serve_models >= 2:
            if args.serve_e2e or args.serve_stream:
                raise SystemExit("--serve-models is exclusive with "
                                 "--serve-e2e / --serve-stream")
            (value, serve_p50_ms, serve_p99_ms, serve_cold_start_s,
             serve_warmup_s, serve_readback_b, serve_prep_ms,
             serve_pool_doc) = bench_serve_pool(
                 args.batch, args.network, args.serve_models)
            serve_stream_dpf = serve_stream_skip = None
            metric = f"serve_imgs_per_sec_mm{args.serve_models}"
        else:
            (value, serve_p50_ms, serve_p99_ms, serve_cold_start_s,
             serve_warmup_s, serve_readback_b, serve_prep_ms,
             serve_stream_dpf, serve_stream_skip) = bench_serve(
                 args.batch, args.network, serve_e2e=args.serve_e2e,
                 stream=args.serve_stream)
            metric = ("serve_imgs_per_sec_e2e" if args.serve_e2e
                      else "serve_imgs_per_sec")
        infer_method = "engine"  # not comparable to forward-only rows
    elif args.mode == "eval":
        eval_rates = bench_eval(args.batch, args.network)
        value = eval_rates["pipelined"]
        metric = "eval_imgs_per_sec"
        infer_method = "pred_eval"  # whole-eval-loop rate: never
        # comparable to forward-only or loader-only rows
    else:
        value = bench_infer_loader(args.batch, args.network)
        metric = "infer_imgs_per_sec_loader_inclusive"
    # whole-mode wall (warmup + compile + timed loops) and the headline
    # result, in the run's own schema — the loader/tester phase spans from
    # the measured loop land in the same stream
    tel.add(f"bench/{args.mode}", time.perf_counter() - t_bench)
    if args.batch != 1:
        metric += f"_b{args.batch}"
    if args.network != "resnet101":
        metric += f"_{args.network}"
    if args.cfg:
        metric += "_ab"  # overridden config: never a headline number
    if opt_acc is not None:
        metric += "_optacc_ab"  # two-config A/B: never a headline number

    vs = None
    baseline_method = None
    baseline_recorded = False
    if (args.mode == "train" and args.batch == 1
            and args.network == "resnet101" and not args.cfg
            and opt_acc is None):
        # method-consistent ratio (round-4 VERDICT weakness 3): chain-
        # method runs divide by the chain-method baseline ('value_chain',
        # the round-4 clean-window measurement), staged runs by the
        # round-1 staged baseline ('value') — a cross-method ratio mixes
        # a dispatch-free numerator with a dispatch-taxed denominator and
        # reads as speedup that is really measurement
        key = "value" if args.legacy_dispatch else "value_chain"
        base = None
        if os.path.exists(BASELINE_FILE):
            with open(BASELINE_FILE) as f:
                base_doc = json.load(f)
            base = base_doc.get(key)
            if base is None:  # first run of this method: record it
                base_doc[key] = value
                with open(BASELINE_FILE, "w") as f:
                    json.dump(base_doc, f)
        else:
            with open(BASELINE_FILE, "w") as f:
                json.dump({"metric": metric, key: value,
                           "hardware": str(jax.devices()[0]),
                           "config": "resnet101 faster-rcnn end2end 608x1024 b1"},
                          f)
        if base is not None:
            vs = round(value / base, 3)
        else:
            # this run IS the baseline it just wrote — a 1.0 here would
            # read as measured parity in the ledger when nothing was
            # compared; say so explicitly instead
            vs = None
            baseline_recorded = True
        baseline_method = "staged" if args.legacy_dispatch else "chain"
    elif args.mode == "pipeline" and not args.cfg:
        # the pipeline series gets its own baseline key per (batch,
        # network): the number is loader-inclusive and box-dependent,
        # never comparable to the dispatch-free chain/staged train rows
        # (and perf_gate groups by baseline_method, so the r05 chain row
        # is never scored against this series)
        key = "value_pipeline"
        if args.batch != 1:
            key += f"_b{args.batch}"
        if args.network != "resnet101":
            key += f"_{args.network}"
        base_doc = {}
        if os.path.exists(BASELINE_FILE):
            with open(BASELINE_FILE) as f:
                base_doc = json.load(f)
        base = base_doc.get(key)
        if base is None:  # first pipeline run of this shape: record it
            base_doc[key] = value
            with open(BASELINE_FILE, "w") as f:
                json.dump(base_doc, f)
            baseline_recorded = True
        else:
            vs = round(value / base, 3)
        baseline_method = "pipeline"
    elif args.mode == "eval":
        # eval gets its own baseline series per (batch, network): the
        # number is a whole-pred_eval rate (loader + forward + NMS +
        # scoring), never comparable to the other series.  The _ab
        # (--cfg) variants are unscored like everywhere else, but the
        # speedup_vs_serial floor row still gates them — "pipelined
        # beats serial" must hold on any config.
        if not args.cfg:
            key = "value_eval"
            if args.batch != 1:
                key += f"_b{args.batch}"
            if args.network != "resnet101":
                key += f"_{args.network}"
            base_doc = {}
            if os.path.exists(BASELINE_FILE):
                with open(BASELINE_FILE) as f:
                    base_doc = json.load(f)
            base = base_doc.get(key)
            if base is None:  # first eval run of this shape: record it
                base_doc[key] = value
                with open(BASELINE_FILE, "w") as f:
                    json.dump(base_doc, f)
                baseline_recorded = True
            else:
                vs = round(value / base, 3)
            baseline_method = "pred_eval"
    elif args.mode == "serve" and args.serve_cascade and not args.cfg:
        # the cascade serve series gets its own record-on-first-run
        # baseline per (batch, network): a blended small/big rate is
        # never comparable to single-model or pool rows, and perf_gate
        # groups by baseline_method so they never cross
        key = "value_serve_cascade"
        if args.batch != 1:
            key += f"_b{args.batch}"
        if args.network != "resnet101":
            key += f"_{args.network}"
        base_doc = {}
        if os.path.exists(BASELINE_FILE):
            with open(BASELINE_FILE) as f:
                base_doc = json.load(f)
        base = base_doc.get(key)
        if base is None:  # first cascade run of this shape: record it
            base_doc[key] = value
            with open(BASELINE_FILE, "w") as f:
                json.dump(base_doc, f)
            baseline_recorded = True
        else:
            vs = round(value / base, 3)
        baseline_method = "cascade"

    out = {
        "metric": metric,
        "value": round(value, 3),
        "unit": "imgs/sec",
        "vs_baseline": vs,
    }
    if baseline_method is not None:
        out["baseline_method"] = baseline_method
    if baseline_recorded:
        out["baseline_recorded"] = True
    if infer_method is not None:
        out["method"] = infer_method
    if args.mode == "serve":
        out["p50_ms"] = serve_p50_ms
        out["p99_ms"] = serve_p99_ms
        # scripts/perf_gate.py expands these into direction=down rows, so
        # a cold-start regression (lost AOT warm start) fails the gate
        out["cold_start_s"] = serve_cold_start_s
        out["warmup_compile_s"] = serve_warmup_s
        # direction=down in perf_gate too: the e2e readback shrink (full
        # (R,K)+(R,4K) tensors → (B,cap,6) detections) can never silently
        # regress, and host_prep_ms pins the submit-thread prep tax
        out["readback_bytes_per_image"] = serve_readback_b
        out["host_prep_ms"] = serve_prep_ms
        # streaming phase (--serve-stream only): perf_gate expands these
        # into a direction=down dispatches_per_frame series and a
        # skip_fraction FLOOR row — their own families, never scored
        # against the request/response rows (the BENCH_r08 precedent)
        if serve_stream_dpf is not None:
            out["dispatches_per_frame"] = serve_stream_dpf
        if serve_stream_skip is not None:
            out["skip_fraction"] = serve_stream_skip
        # multi-model phase (--serve-models): the pool's scheduler
        # counters ride along for the MULTIMODEL evidence trail
        if serve_pool_doc is not None:
            out["pool"] = serve_pool_doc
        # cascade phase (--serve-cascade): escalation_rate is its own
        # ride-along series (keyed by the cascade metric — validated,
        # never scored against non-cascade rows), the router's counters
        # and gate-time quantiles alongside for the evidence trail
        if serve_cascade_doc is not None:
            out["escalation_rate"] = serve_cascade_doc.get(
                "escalation_rate")
            out["cascade"] = serve_cascade_doc
    if opt_acc is not None:
        out["opt_acc"] = opt_acc
    if eval_rates is not None:
        # one row, three variants (satellite contract: serial vs
        # pipelined vs device-postprocess on the same box); perf_gate
        # expands speedup_vs_serial into an absolute-floor row
        out["eval"] = {
            "serial_imgs_per_sec": round(eval_rates["serial"], 3),
            "pipelined_imgs_per_sec": round(eval_rates["pipelined"], 3),
            "device_post_imgs_per_sec": round(eval_rates["device_post"], 3),
            "speedup_vs_serial": round(
                eval_rates["pipelined"] / max(eval_rates["serial"], 1e-9),
                4),
        }
    if sweep_cells is not None:
        out["cells"] = sweep_cells
    if pipe is not None:
        reg = pipe.get("registry", {})
        out["pipeline"] = {
            "best": pipe["best"],
            "cells": pipe["cells"],
            # the registry proof: programs stays flat across cells that
            # share k (no per-cell recompiles), aot_hit counts warm boots
            "programs": len(reg.get("programs", [])),
            "registry_counters": reg.get("counters", {}),
            "sweep_jsonl": pipe.get("sweep_jsonl"),
        }
        if "tuned_file" in pipe:
            out["pipeline"]["tuned_file"] = pipe["tuned_file"]
            out["pipeline"]["tuned"] = pipe["tuned"]
    if tel.enabled:
        tel.gauge(f"bench/{metric}", value)
    obs.close(extra={"bench": out})
    print(json.dumps(out))


if __name__ == "__main__":
    main()
