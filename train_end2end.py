#!/usr/bin/env python
"""End-to-end Faster R-CNN training driver.

Mirrors the reference's ``train_end2end.py`` argv surface and ``train_net``
flow: generate_config → imdb/roidb (+flip, filter) → AnchorLoader →
params (pretrained overlay + new heads at init) → fit (jitted DP step,
six metrics, Speedometer, epoch checkpoints with the bbox de-normalize
contract, --resume).

TPU specifics: ``--devices N`` picks the data-mesh size (the ``--gpus``
equivalent); ``--synthetic`` trains on generated data with zero files on
disk; ``--num-steps`` caps steps for smoke runs.  Multi-host (the
reference's unscripted ``KVStore('dist_sync')`` tier): run the same
command on every host with ``--dist-auto`` (TPU pod) or the
``--dist-coordinator/--dist-num-processes/--dist-process-id`` triple —
each process loads its slice of every global batch and XLA's collectives
do the cross-host gradient reduce (``parallel/distributed.py``).
"""

from __future__ import annotations

import argparse

import jax

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.data import AnchorLoader
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (CappedLoader, add_common_args,
                                      check_dist_loader, config_from_args,
                                      get_imdb, get_train_roidb,
                                      init_or_load_params, replay_from_args,
                                      setup_parallel, start_observability,
                                      strip_device_prep_for_mesh)
from mx_rcnn_tpu.train import ResilienceOptions, fit


def parse_args():
    parser = argparse.ArgumentParser(description="Train Faster R-CNN end2end")
    add_common_args(parser, train=True)
    parser.add_argument("--profile", default="",
                        help="write an XProf device trace of early steps here")
    # --steps-per-dispatch comes from add_common_args (shared with the
    # alternate-training stage tools since round 5)
    return parser.parse_args()


def train_net(args):
    # rendezvous before anything can touch the jax backend
    plan, pidx, pcount = setup_parallel(args)
    cfg = config_from_args(args, train=True)
    # --device-prep (and a tuned cell that selected it) is single-mesh
    # only: downgrade BEFORE the loader is built, or it would emit raw
    # uint8 batches the mesh path cannot prep
    cfg = strip_device_prep_for_mesh(cfg, plan)
    n_dev = plan.n_data if plan else 1
    batch_size = args.batch_images or n_dev * cfg.TRAIN.BATCH_IMAGES
    if plan and batch_size % n_dev:
        raise ValueError(f"batch_images {batch_size} not divisible by "
                         f"mesh size {n_dev}")

    imdb = get_imdb(args, cfg)
    roidb = get_train_roidb(imdb, cfg)
    # data flywheel (--replay-manifest): mix mined serving captures into
    # the epoch plan; the mix is drawn from the loader's plan RNG, so it
    # replays bit-identically under --auto-resume
    replay_roidb, replay_ratio = replay_from_args(args, cfg)
    loader = AnchorLoader(roidb, cfg, batch_size,
                          shuffle=cfg.TRAIN.SHUFFLE,
                          num_parts=pcount, part_index=pidx,
                          replay_roidb=replay_roidb,
                          replay_ratio=replay_ratio)
    check_dist_loader(plan, batch_size, pcount, pidx)
    if args.num_steps:
        loader = CappedLoader(loader, args.num_steps)
    logger.info("training on %d images, %d steps/epoch, batch %d over %d "
                "device(s)", len(roidb), loader.steps_per_epoch, batch_size,
                n_dev)

    model = build_model(cfg)
    params = init_or_load_params(args, cfg, model, batch_size)
    # live plane (inert without --obs-port): when it configures the sink,
    # fit reuses it (owns_tel=False) and the plane writes the summary
    obs = start_observability(args, "train_end2end", rank=pidx,
                              world=pcount,
                              run_meta={"network": args.network,
                                        "batch_size": batch_size})
    try:
        state = fit(cfg, model, params, loader,
                    begin_epoch=args.begin_epoch, end_epoch=args.end_epoch,
                    plan=plan, prefix=args.prefix, graph="end2end",
                    seed=getattr(args, "seed", 0),
                    frequent=args.frequent, resume=args.resume,
                    profile_dir=getattr(args, "profile", "") or None,
                    telemetry_dir=getattr(args, "telemetry_dir", "") or None,
                    steps_per_dispatch=getattr(args, "steps_per_dispatch", 1),
                    fixed_prefixes=cfg.network.FIXED_PARAMS,
                    resilience=ResilienceOptions.from_args(args))
    finally:
        obs.close()
    return state


if __name__ == "__main__":
    train_net(parse_args())
