#!/usr/bin/env bash
# Round-4 TPU follow-up batch (serial; run only when no other TPU job):
#   1. max-pool bwd microbench (select-and-scatter vs reshape+max)
#   2. VGG16 train bench + step profile.  NOTE: the original round-4 run
#      (r4_tpu_session2.log, headers "(reshape pool)") executed with the
#      reshape+max pool temporarily wired into VGGConv; it measured
#      device-NEUTRAL and was reverted (ops/pool.py records the result).
#      To retry the lever on a libtpu upgrade, point VGGConv's 2x2 pool
#      at ops/pool.max_pool_2x2 again — as committed these legs bench
#      the default nn.max_pool path.
#   3. FPN fused-assign interleaved repeat A/B.  Flags are explicit
#      (the original run relied on a since-reverted default flip);
#      wall A/Bs here flip-flopped with tunnel weather — device profile
#      (scripts/profile_step.py, r4_tpu_session3.log) was the deciding
#      instrument: dense 21.95 vs fused 23.15 ms.
set -x
cd "$(dirname "$0")/.."
LOG=${1:-/root/repo/r4_tpu_session2.log}
{
  echo "=== $(date -u) max-pool bwd microbench"
  python scripts/bench_pool.py

  echo "=== $(date -u) VGG16 train bench"
  python bench.py --network vgg16
  echo "=== $(date -u) VGG16 step profile"
  python scripts/profile_step.py --network vgg16

  echo "=== $(date -u) FPN A/B interleaved: fused 1"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=True
  echo "=== $(date -u) FPN A/B interleaved: dense 1"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=False
  echo "=== $(date -u) FPN A/B interleaved: fused 2"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=True
  echo "=== $(date -u) FPN A/B interleaved: dense 2"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=False
} 2>&1 | tee "$LOG"
