#!/usr/bin/env python
"""Query distributed request traces: trace id → its cross-hop span tree.

  python scripts/trace_query.py --telemetry-dir /tmp/t 0123abcd...
  python scripts/trace_query.py --telemetry-dir /tmp/t --slowest 3
  python scripts/trace_query.py --telemetry-dir /tmp/t --list

Merges every member's span stream under ``--telemetry-dir`` — the live
``spans_<member>.jsonl`` files plus the tail-sampled
``trace_tail_<member>.jsonl`` forensics dumps (deduped by (trace, span)
— a span can appear in both) — groups by trace id, and prints each
requested trace as an indented hop tree with per-hop durations:

  trace 9f2c...e1 — root fabric/route 18.42ms, 6 spans, 3 members
    fabric/route 18.42ms [router] member=m0 status=200
      frontend/predict 17.90ms [member0] status=200
        engine/request 16.77ms [member0] rid=12 peers=[13,14] ...
          engine/dispatch 9.31ms [member0] batch_rids=[12,13,14] ...
            engine/forward 7.02ms [member0]

The tree hangs children from parent span ids (``psid`` → ``sid``);
spans whose parent never landed (a crashed hop, a member whose file was
lost) print as extra roots rather than vanishing.  ``--slowest N``
ranks traces by their ROOT span duration — the client-observed hop —
and prints the N worst, which is the "why was my p99 bad" entry point;
``--list`` prints one summary line per trace.  Trace ids may be
abbreviated to any unambiguous prefix.  Pure stdlib — no jax, no numpy;
safe anywhere the telemetry dir is mounted.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.telemetry.tracectx import (SPANS_PREFIX,  # noqa: E402
                                            TAIL_PREFIX)

# attrs printed inline after the hop name, in this order when present;
# anything else prints afterward alphabetically
ATTR_ORDER = ("status", "member", "rid", "peers", "batch_rids",
              "queue_pos", "queue_wait_ms", "pad_frac", "bucket",
              "occupancy", "skipped", "model", "hedged", "retried",
              "shed", "error")


def load_spans(telemetry_dir):
    """Every trace span under the dir, live + tail streams merged and
    deduped by (trace, sid).  Torn lines are skipped, not fatal — a
    query against a live run must not die on a mid-write record."""
    by_key = {}
    for prefix in (SPANS_PREFIX, TAIL_PREFIX):
        pattern = os.path.join(telemetry_dir, f"{prefix}*.jsonl")
        for path in sorted(glob.glob(pattern)):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if (not isinstance(rec, dict)
                            or rec.get("kind") != "span"
                            or not rec.get("trace")):
                        continue
                    by_key[(rec["trace"], rec.get("sid"))] = rec
    return list(by_key.values())


def span_start(rec):
    ts = rec.get("ts")
    if ts is not None:
        return float(ts)
    return float(rec.get("t", 0.0)) - float(rec.get("dur_s", 0.0))


def group_traces(spans):
    traces = {}
    for rec in spans:
        traces.setdefault(rec["trace"], []).append(rec)
    for recs in traces.values():
        recs.sort(key=span_start)
    return traces


def roots_of(recs):
    """Tree roots: spans with no parent, plus orphans whose parent span
    never landed (lost member file / crashed hop)."""
    sids = {r.get("sid") for r in recs}
    return [r for r in recs
            if r.get("psid") is None or r["psid"] not in sids]


def root_duration(recs):
    """The trace's client-observed duration: its true root span when
    one landed, else the longest span (best effort on partial trees)."""
    true = [r for r in recs if r.get("psid") is None]
    pool = true or recs
    return max(float(r.get("dur_s", 0.0)) for r in pool)


def format_attrs(rec):
    attrs = dict(rec.get("attrs") or {})
    parts = []
    for key in ATTR_ORDER:
        if key in attrs:
            parts.append(f"{key}={json.dumps(attrs.pop(key))}")
    for key in sorted(attrs):
        parts.append(f"{key}={json.dumps(attrs[key])}")
    return " ".join(parts)


def render_tree(recs, out):
    children = {}
    for r in recs:
        if r.get("psid") is not None:
            children.setdefault(r["psid"], []).append(r)

    def emit(rec, depth):
        dur_ms = float(rec.get("dur_s", 0.0)) * 1e3
        line = (f"{'  ' * depth}{rec.get('name', '?')} {dur_ms:.2f}ms "
                f"[{rec.get('member', '?')}]")
        extra = format_attrs(rec)
        out.append(line + (f" {extra}" if extra else ""))
        for child in sorted(children.get(rec.get("sid"), []),
                            key=span_start):
            emit(child, depth + 1)

    for root in sorted(roots_of(recs), key=span_start):
        emit(root, 1)


def summary_line(trace_id, recs):
    members = sorted({str(r.get("member", "?")) for r in recs})
    true = [r for r in recs if r.get("psid") is None]
    root_name = true[0].get("name", "?") if true else "(no root)"
    return (f"trace {trace_id} — root {root_name} "
            f"{root_duration(recs) * 1e3:.2f}ms, {len(recs)} span(s), "
            f"{len(members)} member(s): {','.join(members)}")


def resolve_ids(traces, wanted):
    """Abbreviated trace ids → full ids (unique prefix match)."""
    out = []
    for w in wanted:
        w = w.strip().lower()
        hits = [t for t in traces if t == w] or sorted(
            t for t in traces if t.startswith(w))
        if not hits:
            raise SystemExit(f"trace_query: no trace matching {w!r} "
                             f"({len(traces)} trace(s) on disk)")
        if len(hits) > 1:
            raise SystemExit(f"trace_query: ambiguous prefix {w!r} "
                             f"matches {len(hits)} traces "
                             f"({', '.join(hits[:4])}...)")
        out.append(hits[0])
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_ids", nargs="*",
                    help="trace id(s) to print (unambiguous prefixes ok)")
    ap.add_argument("--telemetry-dir", required=True, dest="telemetry_dir",
                    help="dir holding spans_<member>.jsonl / "
                         "trace_tail_<member>.jsonl (serve.py --trace-dir)")
    ap.add_argument("--slowest", type=int, default=0,
                    help="print the N traces with the slowest root span")
    ap.add_argument("--list", action="store_true", dest="list_all",
                    help="one summary line per trace, slowest first")
    args = ap.parse_args(argv)

    spans = load_spans(args.telemetry_dir)
    traces = group_traces(spans)
    if not traces:
        raise SystemExit(f"trace_query: no trace spans under "
                         f"{args.telemetry_dir} (tracing off, or nothing "
                         f"sampled yet?)")

    by_slow = sorted(traces, key=lambda t: -root_duration(traces[t]))
    if args.list_all:
        for trace_id in by_slow:
            print(summary_line(trace_id, traces[trace_id]))
        return
    chosen = resolve_ids(traces, args.trace_ids)
    if args.slowest > 0:
        chosen.extend(t for t in by_slow[:args.slowest]
                      if t not in chosen)
    if not chosen:
        raise SystemExit("trace_query: pass trace id(s), --slowest N, "
                         "or --list")
    for trace_id in chosen:
        recs = traces[trace_id]
        lines = [summary_line(trace_id, recs)]
        render_tree(recs, lines)
        print("\n".join(lines))


if __name__ == "__main__":
    main()
