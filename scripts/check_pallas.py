#!/usr/bin/env python
"""Kernel-vs-oracle equivalence + timing on the REAL TPU chip.

Run manually (pytest runs on the CPU mesh where Mosaic can't lower; there
``nms_pallas`` delegates to the oracle, so CPU tests can't catch kernel
bugs).  Exits nonzero on any mismatch.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.kernels.nms_pallas import nms_pallas
from mx_rcnn_tpu.ops.nms import nms_padded

assert jax.default_backend() == "tpu", "run on the TPU chip"


def gen(n, seed, spread=800.0, size=150.0):
    rng = np.random.RandomState(seed)
    ctr = rng.rand(n, 2) * spread
    wh = rng.rand(n, 2) * size + 10
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], 1).astype(np.float32)
    scores = np.sort(rng.rand(n).astype(np.float32))[::-1].copy()
    return jnp.asarray(boxes), jnp.asarray(scores)


fails = 0
for seed in range(5):
    for n, max_out, thresh in ((2048, 300, 0.7), (6000, 300, 0.7),
                               (12000, 2000, 0.7), (4000, 100, 0.3),
                               (100, 300, 0.5),   # n < max_out shape contract
                               (4097, 300, 0.7),  # pad-boundary crossing
                               (4000, 300, 0.99),  # almost nothing suppressed
                               (4000, 300, 0.01)):  # almost all suppressed
        boxes, scores = gen(n, seed)
        valid = jnp.asarray(np.random.RandomState(seed).rand(n) > 0.02)
        ki_p, km_p = jax.device_get(nms_pallas(boxes, scores, max_out=max_out,
                                               iou_thresh=thresh, valid=valid))
        ki_r, km_r = jax.device_get(nms_padded(boxes, scores, max_out=max_out,
                                               iou_thresh=thresh, valid=valid))
        ok = (km_p.sum() == km_r.sum()
              and np.array_equal(ki_p[km_p], ki_r[km_r]))
        if not ok:
            fails += 1
            print(f"MISMATCH n={n} max_out={max_out} t={thresh} seed={seed}: "
                  f"kept {km_p.sum()} vs {km_r.sum()}")

# adversarial structure: exact ties / identical boxes / all-invalid
box1 = jnp.tile(jnp.asarray([[10., 10., 60., 60.]], jnp.float32), (512, 1))
sc1 = jnp.asarray(np.sort(np.random.RandomState(0).rand(512)
                          .astype(np.float32))[::-1].copy())
for name, (b, s, mo, t, v) in {
    "identical-boxes": (box1, sc1, 300, 0.7, None),
    "all-invalid": (box1, sc1, 300, 0.7, jnp.zeros((512,), bool)),
    "single-box": (box1[:1], sc1[:1], 300, 0.7, None),
}.items():
    ki_p, km_p = jax.device_get(nms_pallas(b, s, max_out=mo, iou_thresh=t,
                                           valid=v))
    ki_r, km_r = jax.device_get(nms_padded(b, s, max_out=mo, iou_thresh=t,
                                           valid=v))
    if km_p.sum() != km_r.sum() or not np.array_equal(ki_p[km_p], ki_r[km_r]):
        fails += 1
        print(f"MISMATCH [{name}]: kept {km_p.sum()} vs {km_r.sum()}")
# batched path (vmap over images — the detector's B>1 shape; exercises the
# custom_vmap → lax.map rule, which Mosaic can't auto-batch)
bb = jnp.stack([gen(2048, s)[0] for s in range(3)])
ss = jnp.stack([gen(2048, s)[1] for s in range(3)])
# per-image DIFFERENT invalid holes: a batching-rule regression that drops
# or broadcasts the valid mask must fail this, not just the all-True case
vv = jnp.stack([jnp.asarray(np.random.RandomState(100 + s).rand(2048) > 0.05)
                for s in range(3)])
ki_b, km_b = jax.device_get(jax.vmap(
    lambda b, s, v: nms_pallas(b, s, max_out=300, iou_thresh=0.7, valid=v)
)(bb, ss, vv))
for b in range(3):
    ki_r, km_r = jax.device_get(nms_padded(bb[b], ss[b], max_out=300,
                                           iou_thresh=0.7, valid=vv[b]))
    if km_b[b].sum() != km_r.sum() or not np.array_equal(
            ki_b[b][km_b[b]], ki_r[km_r]):
        fails += 1
        print(f"MISMATCH [vmap b={b}]: kept {km_b[b].sum()} vs {km_r.sum()}")

print("equivalence:", "FAIL" if fails else "OK")

# timing (chained, fence by readback)
boxes, scores = gen(12000, 0)
for name, f in (("pallas", lambda: nms_pallas(boxes, scores, max_out=2000,
                                              iou_thresh=0.7)),
                ("scan  ", lambda: nms_padded(boxes, scores, max_out=2000,
                                              iou_thresh=0.7))):
    r = f()
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(20):
        r = f()
    _ = np.asarray(jax.device_get(r[0]))[0]
    print(f"{name} 12000->2000: {(time.time() - t0) / 20 * 1000:.1f} ms")

raise SystemExit(1 if fails else 0)
