#!/usr/bin/env python
"""Kernel-vs-oracle equivalence + timing on the REAL TPU chip.

Run manually (pytest runs on the CPU mesh where Mosaic can't lower; there
``nms_pallas`` delegates to the oracle, so CPU tests can't catch kernel
bugs).  Exits nonzero on any mismatch.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.kernels.nms_pallas import nms_pallas
from mx_rcnn_tpu.ops.nms import nms_padded

assert jax.default_backend() == "tpu", "run on the TPU chip"


def gen(n, seed, spread=800.0, size=150.0):
    rng = np.random.RandomState(seed)
    ctr = rng.rand(n, 2) * spread
    wh = rng.rand(n, 2) * size + 10
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], 1).astype(np.float32)
    scores = np.sort(rng.rand(n).astype(np.float32))[::-1].copy()
    return jnp.asarray(boxes), jnp.asarray(scores)


fails = 0
for seed in range(5):
    for n, max_out, thresh in ((2048, 300, 0.7), (6000, 300, 0.7),
                               (12000, 2000, 0.7), (4000, 100, 0.3),
                               (100, 300, 0.5),   # n < max_out shape contract
                               (4097, 300, 0.7),  # pad-boundary crossing
                               (4000, 300, 0.99),  # almost nothing suppressed
                               (4000, 300, 0.01)):  # almost all suppressed
        boxes, scores = gen(n, seed)
        valid = jnp.asarray(np.random.RandomState(seed).rand(n) > 0.02)
        ki_p, km_p = jax.device_get(nms_pallas(boxes, scores, max_out=max_out,
                                               iou_thresh=thresh, valid=valid))
        ki_r, km_r = jax.device_get(nms_padded(boxes, scores, max_out=max_out,
                                               iou_thresh=thresh, valid=valid))
        ok = (km_p.sum() == km_r.sum()
              and np.array_equal(ki_p[km_p], ki_r[km_r]))
        if not ok:
            fails += 1
            print(f"MISMATCH n={n} max_out={max_out} t={thresh} seed={seed}: "
                  f"kept {km_p.sum()} vs {km_r.sum()}")

# adversarial structure: exact ties / identical boxes / all-invalid
box1 = jnp.tile(jnp.asarray([[10., 10., 60., 60.]], jnp.float32), (512, 1))
sc1 = jnp.asarray(np.sort(np.random.RandomState(0).rand(512)
                          .astype(np.float32))[::-1].copy())
for name, (b, s, mo, t, v) in {
    "identical-boxes": (box1, sc1, 300, 0.7, None),
    "all-invalid": (box1, sc1, 300, 0.7, jnp.zeros((512,), bool)),
    "single-box": (box1[:1], sc1[:1], 300, 0.7, None),
}.items():
    ki_p, km_p = jax.device_get(nms_pallas(b, s, max_out=mo, iou_thresh=t,
                                           valid=v))
    ki_r, km_r = jax.device_get(nms_padded(b, s, max_out=mo, iou_thresh=t,
                                           valid=v))
    if km_p.sum() != km_r.sum() or not np.array_equal(ki_p[km_p], ki_r[km_r]):
        fails += 1
        print(f"MISMATCH [{name}]: kept {km_p.sum()} vs {km_r.sum()}")
# batched path (vmap over images — the detector's B>1 shape; exercises the
# custom_vmap → lax.map rule, which Mosaic can't auto-batch)
bb = jnp.stack([gen(2048, s)[0] for s in range(3)])
ss = jnp.stack([gen(2048, s)[1] for s in range(3)])
# per-image DIFFERENT invalid holes: a batching-rule regression that drops
# or broadcasts the valid mask must fail this, not just the all-True case
vv = jnp.stack([jnp.asarray(np.random.RandomState(100 + s).rand(2048) > 0.05)
                for s in range(3)])
ki_b, km_b = jax.device_get(jax.vmap(
    lambda b, s, v: nms_pallas(b, s, max_out=300, iou_thresh=0.7, valid=v)
)(bb, ss, vv))
for b in range(3):
    ki_r, km_r = jax.device_get(nms_padded(bb[b], ss[b], max_out=300,
                                           iou_thresh=0.7, valid=vv[b]))
    if km_b[b].sum() != km_r.sum() or not np.array_equal(
            ki_b[b][km_b[b]], ki_r[km_r]):
        fails += 1
        print(f"MISMATCH [vmap b={b}]: kept {km_b[b].sum()} vs {km_r.sum()}")

# ---- fused assign-IoU reductions (kernels/assign_pallas.py) ------------
# ULP-level parity contract (see kernel docstring): floats to ~2 ulp,
# discrete outputs exact away from ULP-boundaries.
from mx_rcnn_tpu.kernels.assign_pallas import assign_reduce_pallas
from mx_rcnn_tpu.ops.anchors import all_anchors, generate_anchors
from mx_rcnn_tpu.ops.boxes import bbox_overlaps

ULP = 3e-7
for fh, fw, stride, n_gt, seed in ((38, 64, 16, 20, 0), (38, 64, 16, 0, 1),
                                   (152, 256, 4, 50, 2)):
    rng = np.random.RandomState(seed)
    anchors = all_anchors(fh, fw, stride, generate_anchors())
    im_h, im_w = fh * stride, fw * stride
    gt = np.zeros((100, 4), np.float32)
    for i in range(n_gt):
        x1, y1 = rng.rand(2) * np.array([im_w - 200, im_h - 200])
        gt[i] = [x1, y1, x1 + 20 + rng.rand() * 160, y1 + 20 + rng.rand() * 160]
    valid = np.arange(100) < n_gt
    inside = ((anchors[:, 0] >= 0) & (anchors[:, 1] >= 0)
              & (anchors[:, 2] < im_w) & (anchors[:, 3] < im_h))
    ov = np.asarray(bbox_overlaps(jnp.asarray(anchors), jnp.asarray(gt)))
    ov = np.where(valid[None, :], ov, -1.0)
    mx, am = ov.max(1), ov.argmax(1)
    ov_in = np.where(inside[:, None], ov, -1.0)
    gm = ov_in.max(0)
    tie = ((ov_in == gm[None, :]) & valid[None, :] & (gm[None, :] > 0)).any(1)
    k_mx, k_am, k_gm, k_tie = jax.device_get(assign_reduce_pallas(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
        jnp.asarray(inside)))
    # distances over VALID columns only — padded columns' -1.0 sentinels
    # sit at distance 0 of gm and would mark every anchor marginal,
    # making the discrete checks vacuous (test_assign_sample.py pitfall)
    near_tie = (np.abs(ov[:, valid] - ov.max(1, keepdims=True))
                < ULP).sum(1) > 1
    near_gm = ((np.abs(ov[:, valid] - gm[valid][None, :]) < ULP).any(1)
               if valid.any() else np.zeros(ov.shape[0], bool))
    marginal = near_tie | near_gm
    ok = (np.allclose(k_mx, mx, rtol=0, atol=ULP)
          and np.allclose(k_gm, gm, rtol=0, atol=ULP)
          and not ((k_am != am) & ~marginal).any()
          and not ((k_tie != tie) & ~marginal).any())
    if not ok:
        fails += 1
        print(f"MISMATCH [assign fh={fh} n_gt={n_gt}]: "
              f"mx {np.abs(k_mx - mx).max():.2e} "
              f"am {((k_am != am) & ~marginal).sum()} "
              f"tie {((k_tie != tie) & ~marginal).sum()}")

print("equivalence:", "FAIL" if fails else "OK")

# timing (chained, fence by readback)
boxes, scores = gen(12000, 0)
for name, f in (("pallas", lambda: nms_pallas(boxes, scores, max_out=2000,
                                              iou_thresh=0.7)),
                ("scan  ", lambda: nms_padded(boxes, scores, max_out=2000,
                                              iou_thresh=0.7))):
    r = f()
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(20):
        r = f()
    _ = np.asarray(jax.device_get(r[0]))[0]
    print(f"{name} 12000->2000: {(time.time() - t0) / 20 * 1000:.1f} ms")

# timing: fused assign kernel vs dense XLA reductions near FPN scale
# (P2 dominates FPN's 155 520 concatenated anchors; G = 100 like COCO)
anchors_t = jnp.asarray(all_anchors(152, 256, 4,
                                    generate_anchors(scales=(8,))))
rng = np.random.RandomState(0)
gt_t = np.zeros((100, 4), np.float32)
for i in range(60):
    x1, y1 = rng.rand(2) * np.array([800, 400])
    gt_t[i] = [x1, y1, x1 + 20 + rng.rand() * 160, y1 + 20 + rng.rand() * 160]
gt_t = jnp.asarray(gt_t)
valid_t = jnp.asarray(np.arange(100) < 60)
inside_t = jnp.asarray(np.random.RandomState(1).rand(
    anchors_t.shape[0]) > 0.3)


@jax.jit
def dense_reduce(anchors, gt, gv, ins):
    ov = bbox_overlaps(anchors, gt)
    ov = jnp.where(gv[None, :], ov, -1.0)
    ov_in = jnp.where(ins[:, None], ov, -1.0)
    gm = jnp.max(ov_in, axis=0)
    return (jnp.max(ov, axis=1), jnp.argmax(ov, axis=1), gm,
            jnp.any((ov_in == gm[None, :]) & gv[None, :]
                    & (gm[None, :] > 0), axis=1))


for name, f in (("assign fused", lambda: assign_reduce_pallas(
                    anchors_t, gt_t, valid_t, inside_t)),
                ("assign dense", lambda: dense_reduce(
                    anchors_t, gt_t, valid_t, inside_t))):
    r = f()
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(50):
        r = f()
    _ = np.asarray(jax.device_get(r[0]))[0]
    print(f"{name} @116736x100: {(time.time() - t0) / 50 * 1000:.2f} ms")

raise SystemExit(1 if fails else 0)
