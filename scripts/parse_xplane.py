#!/usr/bin/env python
"""Minimal XProf xplane.pb parser: per-op device-time totals without
tensorboard (the installed tensorboard_plugin_profile is incompatible with
this TF's protobuf).  Hand-rolled protobuf wire-format walk over the XSpace
schema (planes=1; XPlane: name=2, lines=3, event_metadata=4; XLine:
name=2, events=4; XEvent: metadata_id=1, duration_ps=3).

Usage:
  python - <<'PY'
  with jax.profiler.trace("/tmp/prof"): ...   # run the jitted fn a few times
  PY
  python scripts/parse_xplane.py /tmp/prof/plugins/profile/*/vm.xplane.pb [topN]

Reading the output: the 'XLA Modules' line gives whole-program device time
per jit call (the trustworthy number — wall clock on the tunneled device
adds ~2.4 ms dispatch per chained call and swamps sub-ms effects);
'XLA Ops' rows are per-op busy times grouped by op family + output
shape; 'Async XLA Ops' spans overlap compute and must not be summed.
Each line's busy total naively sums event durations — valid for the
serial Modules/Ops lines, an overestimate on any line with overlapping
spans.
"""

import struct, collections, sys, re

def read_varint(buf, i):
    r, s = 0, 0
    while True:
        b = buf[i]; i += 1
        r |= (b & 0x7f) << s
        if not b & 0x80:
            return r, i
        s += 7

def parse_fields(buf):
    i, n = 0, len(buf)
    while i < n:
        key, i = read_varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = read_varint(buf, i)
        elif wt == 2:
            ln, i = read_varint(buf, i)
            v = buf[i:i+ln]; i += ln
        elif wt == 5:
            v = struct.unpack("<I", buf[i:i+4])[0]; i += 4
        elif wt == 1:
            v = struct.unpack("<Q", buf[i:i+8])[0]; i += 8
        else:
            raise ValueError(f"wt {wt}")
        yield fno, wt, v

def iter_tpu_lines(path):
    """Yield (plane_name, line_name, [(op_name, duration_ps), ...]) for every
    line of every TPU plane in the capture.  Multi-chip captures yield one
    group of lines per device plane."""
    data = open(path, "rb").read()
    for fno, wt, plane_buf in parse_fields(data):
        if fno != 1:
            continue
        plane_name, meta, lines = None, {}, []
        for f2, w2, v2 in parse_fields(plane_buf):
            if f2 == 2 and w2 == 2:
                plane_name = v2.decode(errors="replace")
            elif f2 == 4 and w2 == 2:
                k = name = None
                for f3, w3, v3 in parse_fields(v2):
                    if f3 == 1 and w3 == 0: k = v3
                    elif f3 == 2 and w3 == 2:
                        for f4, w4, v4 in parse_fields(v3):
                            if f4 == 2 and w4 == 2:
                                name = v4.decode(errors="replace")
                if k is not None:
                    meta[k] = name
            elif f2 == 3 and w2 == 2:
                lines.append(v2)
        if "TPU" not in (plane_name or ""):
            continue
        for lb in lines:
            line_name = None
            evs = []
            for f3, w3, v3 in parse_fields(lb):
                if f3 == 2 and w3 == 2:
                    try: line_name = v3.decode()
                    except Exception: pass
                if f3 == 4 and w3 == 2:  # XLine.events (probed empirically)
                    try:
                        mid = dur = None
                        for f4, w4, v4 in parse_fields(v3):
                            if f4 == 1 and w4 == 0: mid = v4
                            elif f4 == 3 and w4 == 0: dur = v4
                        if mid is not None and dur is not None and mid in meta:
                            evs.append((meta[mid], dur))
                    except Exception:
                        pass
            if evs:
                yield plane_name, line_name, evs

def xplane_lines(path):
    """Library form: -> {line_name: (n_events, total_ms, fam, full)} where
    ``fam`` maps op-family → ms and ``full`` maps full op name → ms.
    Multi-chip captures are AGGREGATED across device planes (totals are the
    sum over all cores)."""
    out = {}
    for plane_name, line_name, evs in iter_tpu_lines(path):
        n0, t0, fam, full = out.setdefault(
            line_name, (0, 0.0, collections.Counter(), collections.Counter()))
        for name, d in evs:
            m = re.match(r"%?([a-zA-Z_\-]+)", name)
            fam[m.group(1) if m else name] += d / 1e9
            full[name] += d / 1e9
        out[line_name] = (n0 + len(evs),
                          t0 + sum(d for _, d in evs) / 1e9, fam, full)
    return out

def main(path, topn=20):
    for plane_name, line_name, evs in iter_tpu_lines(path):
        total = collections.Counter()
        for name, d in evs:
            # group by op family + dtype/shape
            fam = re.match(r"%?([a-zA-Z_\-]+)", name)
            k2 = fam.group(1) if fam else name
            tm = re.search(r"= ((?:bf16|f32|s32|u32|s8|pred|u8)\[[^\]]*\])", name)
            if tm: k2 += " " + tm.group(1)
            total[k2] += d
        print(f"-- line '{line_name}' on {plane_name}: {len(evs)} events, busy {sum(d for _, d in evs)/1e9:.2f} ms")
        for nm, ps in total.most_common(topn):
            print(f"  {ps/1e9:9.3f} ms  {nm[:95]}")

if __name__ == "__main__":
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    topn = 15
    paths = sys.argv[1:]
    if len(paths) > 1 and paths[-1].isdigit():  # trailing topN after glob paths
        topn = int(paths[-1])
        paths = paths[:-1]
    for _p in paths:
        if len(paths) > 1:
            print(f"==== {_p}")
        main(_p, topn)
