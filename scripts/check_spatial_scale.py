#!/usr/bin/env python
"""Spatial-parallel FPN parity at REALISTIC resolution (round-3 VERDICT
weakness: sp was validated only at toy shapes; PARITY.md claims it for
aerial/medical-tile-class inputs).

Runs one FPN train step at 512×640 f32 — the production SCALES ballpark —
on the virtual 8-device CPU mesh, (data=2, space=4) vs flat (data=2), and
asserts loss parity.  A one-shot script, not a suite test: the CPU-mesh
compile of a 512×640 pyramid step takes minutes (run it when touching
anything sharding-adjacent; the suite keeps the fast 128×96 version).

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/check_spatial_scale.py
"""

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh, shard_batch
from mx_rcnn_tpu.train import create_train_state, make_train_step

H, W = 512, 640
B = 2

cfg = generate_config("resnet101_fpn", "PascalVOC")
cfg = cfg.replace(
    tpu=dataclasses.replace(cfg.tpu, SCALES=((H, W),), MAX_GT=12,
                            COMPUTE_DTYPE="float32"),
    network=dataclasses.replace(cfg.network,
                                PIXEL_STDS=(127.0, 127.0, 127.0)),
    TRAIN=dataclasses.replace(cfg.TRAIN, RPN_PRE_NMS_TOP_N=2000,
                              RPN_POST_NMS_TOP_N=256, BATCH_ROIS=64),
)

rng = np.random.RandomState(0)
gtb = np.zeros((B, 12, 4), np.float32)
gtc = np.zeros((B, 12), np.int32)
gtv = np.zeros((B, 12), bool)
for b in range(B):
    for j in range(8):
        x1, y1 = rng.randint(0, W - 200), rng.randint(0, H - 200)
        gtb[b, j] = (x1, y1, x1 + rng.randint(40, 199),
                     y1 + rng.randint(40, 199))
        gtc[b, j] = rng.randint(1, 21)
        gtv[b, j] = True
batch = dict(
    images=rng.randn(B, H, W, 3).astype(np.float32),
    im_info=np.tile(np.asarray([[H, W, 1.0]], np.float32), (B, 1)),
    gt_boxes=gtb, gt_classes=gtc, gt_valid=gtv,
)

model = build_model(cfg)
params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (H, W))

losses = {}
for name, plan in (("dp", make_mesh(jax.devices()[:2], data=2)),
                   ("dp_sp", make_mesh(data=2, space=4))):
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
    step = make_train_step(model, tx, plan=plan, trainable_mask=mask)
    state = jax.device_put(state, plan.replicated())
    t0 = time.time()
    run = []
    for i in range(2):
        sb = shard_batch(plan, batch)
        if plan.n_space > 1:
            assert "space" in str(sb["images"].sharding.spec)
        state, metrics = step(state, sb, jax.random.PRNGKey(i))
        run.append(float(jax.device_get(metrics["total_loss"])))
    losses[name] = run
    print(f"{name}: losses={run} ({time.time() - t0:.0f}s incl. compile)")

np.testing.assert_allclose(losses["dp"], losses["dp_sp"], rtol=1e-4)
print(f"OK: FPN sp parity at {H}x{W} f32, (data=2, space=4) vs flat dp")
