#!/usr/bin/env python
"""Query watchtower alerts: the lifecycle log, the live endpoint, and
metric-history sparklines.

  python scripts/alert_query.py --telemetry-dir /tmp/t --list
  python scripts/alert_query.py --telemetry-dir /tmp/t fabric_p99_burn
  python scripts/alert_query.py --port 8320 --live
  python scripts/alert_query.py --port 8320 --history fabric/route_time
  python scripts/alert_query.py --telemetry-dir /tmp/t \\
      --assert fabric_p99_burn=resolved --require-traces fabric_p99_burn

Offline mode folds every ``alerts_<member>.jsonl`` under
``--telemetry-dir`` (the watchtower's atomic transition log,
telemetry/watch.py) and prints per-alert timelines: each
pending→firing→resolved transition with its value, hold/firing
durations, and the tail trace ids the firing transition attached — the
join point into ``scripts/trace_query.py`` ("this alert fired; here are
the slow traces from the same window").

Live mode (--host/--port or --unix-socket against a serve.py --watch
process) prints the ``/alerts`` document — firing / pending / silenced
/ resolved instances plus active silences — and ``--history METRIC``
renders the watchtower's in-process metric ring for one series as a
unicode sparkline over ``--window`` seconds.

Assertions for smoke scripts: ``--assert NAME=STATE`` (repeatable)
exits 1 unless the LATEST transition of NAME is STATE — so
``--assert fabric_p99_burn=resolved`` pins the full fire-then-recover
arc; ``--require-traces NAME`` exits 1 unless some firing transition of
NAME carried at least one trace id (the alert→trace join the flight
dump relies on).  Pure stdlib — no jax, no numpy; safe anywhere the
telemetry dir is mounted.
"""

import argparse
import glob
import http.client
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.telemetry.watch import ALERTS_PREFIX  # noqa: E402

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def load_transitions(telemetry_dir):
    """Every ``kind: "alert"`` record under the dir, time-ordered.
    Torn lines are skipped, not fatal — the log is rewritten atomically
    but a query against a live run must not die on a race."""
    recs = []
    pattern = os.path.join(telemetry_dir, f"{ALERTS_PREFIX}*.jsonl")
    for path in sorted(glob.glob(pattern)):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind") == "alert":
                    recs.append(rec)
    recs.sort(key=lambda r: float(r.get("t", 0.0)))
    return recs


def by_alert(recs):
    out = {}
    for rec in recs:
        out.setdefault(str(rec.get("alert", "?")), []).append(rec)
    return out


def latest_state(recs):
    """The alert's current state: the latest transition per fingerprint,
    with 'firing' winning over anything else across instances (one
    member still firing means the alert is firing)."""
    last = {}
    for rec in recs:
        last[rec.get("fingerprint", "?")] = str(rec.get("state", "?"))
    states = set(last.values())
    for state in ("firing", "pending", "resolved"):
        if state in states:
            return state
    return next(iter(states), "?")


def trace_ids_of(recs):
    ids = []
    for rec in recs:
        for tid in rec.get("trace_ids") or []:
            if tid not in ids:
                ids.append(tid)
    return ids


def summary_line(name, recs):
    states = [str(r.get("state", "?")) for r in recs]
    firing_s = sum(float(r.get("firing_s", 0.0)) for r in recs
                   if isinstance(r.get("firing_s"), (int, float)))
    members = sorted({str(r.get("member", "?")) for r in recs})
    return (f"{name} [{recs[0].get('severity', '?')}] — "
            f"{latest_state(recs)}; {len(recs)} transition(s) "
            f"(fired {states.count('firing')}, resolved "
            f"{states.count('resolved')}), {firing_s:.2f}s firing, "
            f"member(s): {','.join(members)}")


def format_labels(labels):
    return ",".join(f"{k}={v}" for k, v in sorted((labels or {}).items()))


def render_timeline(name, recs, out):
    t0 = float(recs[0].get("t", 0.0))
    for rec in recs:
        parts = [f"  +{float(rec.get('t', 0.0)) - t0:9.2f}s",
                 f"{rec.get('state', '?'):<9}",
                 f"[{rec.get('member', '?')}]"]
        labels = format_labels(rec.get("labels"))
        if labels:
            parts.append(labels)
        v = rec.get("value")
        if isinstance(v, (int, float)):
            parts.append(f"value={v:g}")
        for key in ("held_s", "firing_s"):
            if isinstance(rec.get(key), (int, float)):
                parts.append(f"{key}={rec[key]:g}")
        if rec.get("silenced"):
            parts.append("silenced")
        traces = rec.get("trace_ids") or []
        if traces:
            parts.append(f"traces=[{','.join(t[:8] for t in traces)}]")
        out.append(" ".join(parts))


def http_get_json(args, path):
    """``(status, doc)`` for GET ``path`` against the live target;
    raises SystemExit on connection failure (a live query against a
    dead server is an operator error worth a clean message)."""
    try:
        if args.unix_socket:
            conn = _UnixConn(args.unix_socket, args.timeout)
        else:
            conn = http.client.HTTPConnection(args.host, args.port,
                                              timeout=args.timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            doc = json.loads(body) if body else {}
            return resp.status, doc
        finally:
            conn.close()
    except (OSError, ValueError) as e:
        target = args.unix_socket or f"{args.host}:{args.port}"
        raise SystemExit(f"alert_query: {target}{path} unreachable "
                         f"({e})")


class _UnixConn(http.client.HTTPConnection):
    def __init__(self, sock_path, timeout):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._sock_path)
        self.sock = s


def sparkline(values, width=60):
    """Min-max normalized unicode sparkline, downsampled to ``width``
    by taking the max of each chunk (spikes must stay visible)."""
    if not values:
        return "(no points)"
    if len(values) > width:
        chunk = len(values) / width
        values = [max(values[int(i * chunk):
                             max(int((i + 1) * chunk), int(i * chunk) + 1)])
                  for i in range(width)]
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(SPARK_BLOCKS[min(int((v - lo) / span
                                        * (len(SPARK_BLOCKS) - 1)),
                                    len(SPARK_BLOCKS) - 1)]
                   for v in values)


def render_live(doc, out):
    out.append(f"member {doc.get('member', '?')} — "
               f"{doc.get('rules', 0)} rule(s), "
               f"{doc.get('ticks', 0)} tick(s)")
    for section in ("firing", "pending", "silenced"):
        for inst in doc.get(section) or []:
            labels = format_labels(inst.get("labels"))
            line = (f"  {section:<9} {inst.get('alert', '?')} "
                    f"[{inst.get('severity', '?')}] "
                    f"since {inst.get('since_s', 0.0):g}s "
                    f"value={inst.get('value')}")
            if labels:
                line += f" {labels}"
            traces = inst.get("trace_ids") or []
            if traces:
                line += f" traces=[{','.join(t[:8] for t in traces)}]"
            out.append(line)
    for inst in doc.get("resolved") or []:
        out.append(f"  resolved  {inst.get('alert', '?')} "
                   f"[{inst.get('severity', '?')}] "
                   f"{inst.get('age_s', 0.0):g}s ago "
                   f"(fired {inst.get('firing_s', 0.0):g}s)")
    for s in doc.get("silences") or []:
        out.append(f"  silence   {s.get('alertname', '?')} "
                   f"expires in {s.get('expires_in_s', 0.0):g}s "
                   f"(id {s.get('id', '?')})")
    if not any(doc.get(k) for k in ("firing", "pending", "silenced",
                                    "resolved", "silences")):
        out.append("  (no alert instances)")


def run_asserts(grouped, asserts, require_traces):
    """The smoke-script exit-code surface; returns failure lines."""
    failures = []
    for spec in asserts:
        name, sep, state = spec.partition("=")
        if not sep:
            raise SystemExit(f"alert_query: --assert is NAME=STATE, "
                             f"got {spec!r}")
        recs = grouped.get(name)
        if not recs:
            failures.append(f"{name}: no transitions on disk "
                            f"(expected latest state {state!r})")
        elif latest_state(recs) != state:
            failures.append(f"{name}: latest state is "
                            f"{latest_state(recs)!r}, expected {state!r}")
    for name in require_traces:
        recs = grouped.get(name, [])
        fired = [r for r in recs if r.get("state") == "firing"]
        if not fired:
            failures.append(f"{name}: never fired (no trace ids to "
                            f"check)")
        elif not trace_ids_of(fired):
            failures.append(f"{name}: fired with ZERO trace ids "
                            f"attached (tracing off on the member?)")
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("alerts", nargs="*",
                    help="alertname(s) to print timelines for (offline "
                         "mode; default: every alert seen)")
    ap.add_argument("--telemetry-dir", default="", dest="telemetry_dir",
                    help="dir holding alerts_<member>.jsonl (offline "
                         "transition-log mode)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--unix-socket", default="", dest="unix_socket",
                    help="live target over a Unix socket instead of TCP")
    ap.add_argument("--live", action="store_true",
                    help="print the live /alerts document")
    ap.add_argument("--history", default="", metavar="METRIC",
                    help="live mode: sparkline this metric from the "
                         "watchtower's /history ring")
    ap.add_argument("--window", type=float, default=300.0,
                    help="--history window in seconds")
    ap.add_argument("--list", action="store_true", dest="list_all",
                    help="offline mode: one summary line per alert")
    ap.add_argument("--assert", action="append", default=[],
                    dest="asserts", metavar="NAME=STATE",
                    help="exit 1 unless NAME's latest transition is "
                         "STATE (repeatable; offline mode)")
    ap.add_argument("--require-traces", action="append", default=[],
                    dest="require_traces", metavar="NAME",
                    help="exit 1 unless a firing transition of NAME "
                         "carried at least one trace id (repeatable)")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    live_target = bool(args.unix_socket or args.port)
    if args.history:
        if not live_target:
            raise SystemExit("alert_query: --history needs a live "
                             "target (--port/--unix-socket)")
        from urllib.parse import quote
        status, doc = http_get_json(
            args, f"/history?metric={quote(args.history, safe='')}"
                  f"&window={args.window:g}")
        if status != 200:
            raise SystemExit(f"alert_query: /history → {status} "
                             f"({doc.get('error', 'watchtower off?')})")
        vals = [p[1] for p in doc.get("points") or []]
        print(f"{doc.get('metric', args.history)} over last "
              f"{args.window:g}s — {len(vals)} point(s), "
              f"min {doc.get('min', 0):g} max {doc.get('max', 0):g} "
              f"last {doc.get('last', 0):g}")
        print(f"  {sparkline(vals)}")
        return

    if args.live or (live_target and not args.telemetry_dir):
        if not live_target:
            raise SystemExit("alert_query: --live needs "
                             "--port/--unix-socket")
        status, doc = http_get_json(args, "/alerts")
        if status != 200:
            raise SystemExit(f"alert_query: /alerts → {status} "
                             f"(serve.py --watch not active?)")
        lines = []
        render_live(doc, lines)
        print("\n".join(lines))
        return

    if not args.telemetry_dir:
        raise SystemExit("alert_query: pass --telemetry-dir (offline "
                         "log mode) or --port/--unix-socket (live mode)")
    recs = load_transitions(args.telemetry_dir)
    grouped = by_alert(recs)
    if args.asserts or args.require_traces:
        failures = run_asserts(grouped, args.asserts,
                               args.require_traces)
        for f in failures:
            print(f"alert_query: ASSERT {f}", file=sys.stderr)
        if failures:
            sys.exit(1)
        print(f"alert_query: {len(args.asserts)} assert(s) + "
              f"{len(args.require_traces)} trace requirement(s) OK")
        return
    if not grouped:
        raise SystemExit(f"alert_query: no alert transitions under "
                         f"{args.telemetry_dir} (watchtower off, or "
                         f"nothing ever alerted?)")
    if args.list_all:
        for name in sorted(grouped):
            print(summary_line(name, grouped[name]))
        return
    chosen = args.alerts or sorted(grouped)
    for name in chosen:
        if name not in grouped:
            raise SystemExit(f"alert_query: no transitions for {name!r} "
                             f"(have: {', '.join(sorted(grouped))})")
        lines = [summary_line(name, grouped[name])]
        render_timeline(name, grouped[name], lines)
        print("\n".join(lines))


if __name__ == "__main__":
    main()
