#!/usr/bin/env python
"""Open-loop HTTP load generator for serve.py — latency under load.

  python scripts/loadgen.py --host 127.0.0.1 --port 8321 --n 64 --rate 20
  python scripts/loadgen.py --unix-socket /tmp/serve.sock --n 32 --rate 0
  python scripts/loadgen.py --port 8321 --scenario steady --scenario bursty \
      --n 64 --rate 40 --report /tmp/slo.json

Open-loop: request k is FIRED at its scheduled instant regardless of
whether earlier responses came back (each request gets its own thread),
so a slow server accumulates in-flight work and the latency distribution
shows it — closed-loop generators that wait for responses throttle
themselves to the server's pace and hide exactly the queueing behavior
this exists to measure (the coordinated-omission trap).  ``--rate 0``
fires everything at once (burst mode: what backpressure tests want).

Bodies are mixed-size random uint8 images — half landscape, half
portrait, dimensions jittered per request (seeded) — so the server
exercises both orientation buckets and real ``resize_to_bucket`` work.

Scenario profiles (``--scenario``, repeatable — the SLO gate's workload
vocabulary):

* ``steady``   — uniform arrivals at ``--rate`` (the baseline SLO).
* ``bursty``   — same average rate, but arrivals clump into bursts of
  ``--burst`` fired back-to-back: the workload that exposes queue bloat
  and exercises the SLO controller's shed valve.
* ``size-mix`` — steady arrivals, adversarial size jitter (full range
  down to tiny images, random orientation flips): stresses per-bucket
  routing and batch fill.

Without ``--scenario`` one anonymous steady run prints exactly ONE JSON
line (the PR-3 contract):

  {"requests": N, "status": {"200": k, "503": m, ...}, "p50_ms": ...,
   "p99_ms": ..., "error_rate": ..., "mean_queue_wait_ms": ...,
   "imgs_per_sec": ..., "wall_s": ...}

With scenarios, one such line prints per scenario (prefixed by its name
under ``"scenario"``), and ``--report PATH`` additionally writes the
machine-readable SLO report ``scripts/perf_gate.py`` gates:

  {"schema": "mxr_slo_report", "version": 1,
   "scenarios": [{"name": "steady", "requests": ..., "status": {...},
                  "p50_ms": ..., "p99_ms": ..., "error_rate": ...,
                  "availability": ..., "time_to_recover_s": ...,
                  "imgs_per_sec": ..., "wall_s": ...}, ...]}

Failover metrics (ISSUE 8): ``availability`` is the 2xx fraction over
NON-SHED submits (503s are deliberate backpressure, not unavailability);
``time_to_recover_s`` is the gap from the first hard failure (5xx or
transport error) to the next 2xx COMPLETION after it, null when the run
never hard-failed.

latency percentiles are over 2xx responses (client-observed, including
queue wait + forward + post-process + transport); ``imgs_per_sec`` is
2xx responses over the wall from first fire to last response;
``error_rate`` is the non-2xx fraction.  With ``--assert-2xx`` the exit
code is 1 unless every response was 2xx, and the failure line on stderr
names each offending status and its count.  Pure stdlib + numpy; no jax
import, safe on a machine with no accelerator.

Fabric mode (ISSUE 12): with ``--fabric`` the TCP target is a fabric
router (serve.py --fabric) — the router's ``/metrics`` per-member
request counters are snapshotted around every scenario and each output
line/report row gains ``member_share``, the fraction of the scenario's
routed requests each member served (the routing-balance evidence
script/fabric_smoke.sh and the FABRIC_r*.json gate read), plus
``fabric_members``, the live member count at scenario end.

Capture check (ISSUE 13): with ``--capture-check`` the target's
``/metrics`` flywheel ``captured`` counter is snapshotted around the
whole run and the delta must match ``2xx submits / sample_every``
within ``--capture-tolerance`` (exit 1 otherwise) — the smoke-script
guard against silent capture loss.

Stream mode (ISSUE 14): ``--streams N`` switches to the camera model —
N concurrent streams, each a CLOSED loop at ``--fps`` over ONE
persistent keep-alive connection to ``POST /stream``, frames sequenced
per stream.  Closed-loop is deliberate here (the opposite of the
request mode above): a camera cannot fire frame k+1 before frame k's
slot, so a slow server shows up as ``frames_dropped`` (scheduled slots
abandoned because the sender was more than one frame interval late),
not as unbounded in-flight pileup.  ``--motion`` picks the per-frame
pixel dynamics (repeatable — one scenario per profile):

* ``static``    — fixed scene + per-frame sensor noise on ~5% of pixels:
  the skip gate's best case.
* ``pan``       — the scene translates a few pixels per frame: every
  frame differs everywhere, the gate must NOT skip.
* ``scene-cut`` — a new random scene every ``--cut-every`` frames,
  static between cuts: exercises both gate edges.

Each scenario prints one JSON line and contributes one row to the
``--report`` doc, which in stream mode uses schema ``mxr_stream_report``
(per-stream p99 list, max-over-streams ``p99_ms``, ``frames_dropped``,
client-observed ``skip_fraction`` from response ``skipped`` flags, and
``dispatches_per_frame`` diffed from the server's ``/metrics`` engine
counters).  ``--skip-floor``/``--p99-ceiling-ms`` attach the
``perf_gate.py`` floor/ceiling fields to the rows the gate scores.

Multi-model mode (ISSUE 15): ``--models a=0.7,b=0.3`` targets a model
pool (serve.py --models): every request carries a ``"model"`` field
drawn from the given mix (seeded), and two scenarios run —

* ``mixed`` — open-loop steady arrivals, models interleaved per the
  mix: the aggregate-throughput workload.
* ``burst`` — the non-burst models keep their steady share of
  ``--rate`` while ``--burst-model`` (default: the first in the mix)
  fires ALL its requests back-to-back mid-run: the tenant-isolation
  workload — the sibling models' p99 under the burst is what the
  MULTIMODEL gate's isolation ceiling scores.

Each scenario prints one JSON line with per-model ``p50_ms``/
``p99_ms``/``availability``/``error_rate`` blocks under ``"models"``
alongside the aggregate fields, and ``--report`` writes schema
``mxr_multimodel_report``.  ``--throughput-floor`` attaches the
aggregate ``imgs_per_sec`` floor to the mixed row;
``--p99-ceiling-ms`` attaches the isolation ceiling the gate enforces
on every NON-burst model in the burst row.

Cascade mode (ISSUE 19): ``--cascade`` targets a pool serving with a
cascade router (serve.py --cascade small:big — the pair is discovered
from the target's ``/metrics`` cascade section, no flags to repeat).
Two scenarios run over IDENTICAL seeded payloads:

* ``big_only`` — every request addressed straight at the big model
  (``"model": <big>``, bypassing the gate): the throughput baseline
  and the agreement reference.
* ``cascade``  — default routing through the confidence gate; response
  docs are retained so the ``cascade`` provenance field yields the
  client-observed ``escalation_rate`` and the per-class
  (``answered_small`` vs ``escalated``) latency split, and the
  ``detections`` yield ``agreement`` — mean ``detection_agreement``
  (the PR-17 promotion-gate metric) against the big-only answers for
  the same images.

``--report`` writes schema ``mxr_cascade_report``.  The gate pins ride
the cascade row: ``speedup_vs_big`` (cascade imgs/s over big-only
imgs/s, floored by ``--speedup-floor``, default 1.0 — the cascade must
not LOSE to always-big), ``--agreement-floor`` (mean agreement floor),
and ``--throughput-floor`` (absolute imgs/s floor) — what
``perf_gate.py`` scores on CASCADE_r*.json.

``--watch-check`` (ISSUE 20, script/watch_smoke.sh): scrape the
target's ``/alerts`` (a serve.py --watch process) after the run and
assert the alert set — no ``--watch-expect`` means NOTHING may have
fired (the clean-traffic contract); each ``--watch-expect NAME`` must
have fired, and nothing outside the expected set may still be firing.
Each scenario summary gains an ``alerts`` block either way.
"""

import argparse
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.serve.frontend import (encode_image_payload,  # noqa: E402
                                        unix_http_request)

REPORT_SCHEMA = "mxr_slo_report"
STREAM_REPORT_SCHEMA = "mxr_stream_report"
MULTIMODEL_REPORT_SCHEMA = "mxr_multimodel_report"
AUTOSCALE_REPORT_SCHEMA = "mxr_autoscale_report"
CASCADE_REPORT_SCHEMA = "mxr_cascade_report"
REPORT_VERSION = 1
SCENARIOS = ("steady", "bursty", "size-mix")
PROFILES = ("diurnal", "flashcrowd")

# time-varying open-loop profiles (ISSUE 18): per segment a fraction of
# --n fired at a multiple of --rate.  diurnal = piecewise ramp up to a
# peak and back (the daily traffic curve, compressed); flashcrowd = a
# steady baseline with a near-back-to-back spike in the middle — the
# shape a predictive autoscaler must beat
PROFILE_SEGMENTS = {
    "diurnal": ((0.2, 0.4), (0.2, 0.8), (0.2, 1.6), (0.2, 0.8),
                (0.2, 0.4)),
    "flashcrowd": ((0.4, 0.5), (0.4, 8.0), (0.2, 0.5)),
}
MOTIONS = ("static", "pan", "scene-cut")


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--unix-socket", default="", dest="unix_socket",
                    help="target a Unix-socket server instead of TCP")
    ap.add_argument("--n", type=int, default=32,
                    help="requests to fire (per scenario)")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="average arrival rate, req/s (0 = fire all at "
                         "once)")
    ap.add_argument("--scenario", action="append", choices=SCENARIOS,
                    dest="scenarios", default=None,
                    help="run this named profile (repeatable; omit for "
                         "one anonymous steady run)")
    ap.add_argument("--burst", type=int, default=8,
                    help="bursty scenario: requests per burst (fired "
                         "back-to-back; bursts spaced to keep --rate on "
                         "average)")
    ap.add_argument("--profile", default="", choices=("",) + PROFILES,
                    help="time-varying open-loop rate schedule (ISSUE "
                         "18): diurnal = piecewise ramp up/down around "
                         "--rate, flashcrowd = baseline + spike; the "
                         "segment schedule is emitted into the report "
                         "row for reproducibility, and with --report "
                         "the doc becomes an mxr_autoscale_report")
    ap.add_argument("--fleet-poll-s", type=float, default=0.3,
                    dest="fleet_poll_s",
                    help="--profile + --fabric: sample the router's "
                         "ready-member count this often during the run "
                         "(feeds time_to_scale_s)")
    ap.add_argument("--scale-floor", type=float, default=0.0,
                    dest="scale_floor",
                    help="autoscale report: perf_gate floor on peak "
                         "minus starting ready-member count (0 = no "
                         "row)")
    ap.add_argument("--time-to-scale-ceiling-s", type=float, default=0.0,
                    dest="time_to_scale_ceiling_s",
                    help="autoscale report: perf_gate ceiling on "
                         "time_to_scale_s (0 = trend-only row)")
    ap.add_argument("--report", default="",
                    help="write the machine-readable SLO report JSON here "
                         "(scenario mode)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    dest="deadline_ms",
                    help="per-request deadline forwarded to the server "
                         "(0 = server default)")
    ap.add_argument("--short", type=int, default=480,
                    help="short side of generated images (long side is "
                         "--long); pick at or under the server's bucket "
                         "scale")
    ap.add_argument("--long", type=int, default=640, dest="long_")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-request client wait")
    ap.add_argument("--assert-2xx", action="store_true", dest="assert_2xx",
                    help="exit 1 unless every response was 2xx (stderr "
                         "names the offending statuses)")
    ap.add_argument("--fabric", action="store_true",
                    help="target is a fabric router: diff its /metrics "
                         "per-member request counters around each "
                         "scenario and report member_share (TCP only)")
    ap.add_argument("--capture-check", action="store_true",
                    dest="capture_check",
                    help="diff the server's /metrics flywheel captured "
                         "counter around the run and exit 1 unless it "
                         "matches 2xx submits / capture sample rate "
                         "within --capture-tolerance (catches silent "
                         "capture loss in smoke scripts)")
    ap.add_argument("--capture-tolerance", type=float, default=0.1,
                    dest="capture_tolerance",
                    help="--capture-check: allowed relative deviation "
                         "of captured-delta from the expected count")
    ap.add_argument("--streams", type=int, default=0,
                    help="stream mode: this many concurrent sequenced "
                         "streams against POST /stream (0 = classic "
                         "request mode)")
    ap.add_argument("--fps", type=float, default=10.0,
                    help="stream mode: per-stream frame rate (0 = send "
                         "frames back-to-back)")
    ap.add_argument("--frames", type=int, default=32,
                    help="stream mode: frames per stream")
    ap.add_argument("--motion", action="append", choices=MOTIONS,
                    dest="motions", default=None,
                    help="stream mode: motion profile (repeatable — one "
                         "scenario per profile; default static)")
    ap.add_argument("--cut-every", type=int, default=8, dest="cut_every",
                    help="scene-cut profile: frames between scene "
                         "changes")
    ap.add_argument("--skip-floor", type=float, default=0.0,
                    dest="skip_floor",
                    help="stream mode: attach this skip_fraction floor "
                         "to the static-profile report row (what "
                         "perf_gate.py enforces)")
    ap.add_argument("--p99-ceiling-ms", type=float, default=0.0,
                    dest="p99_ceiling_ms",
                    help="stream mode: per-stream p99 ceiling attached "
                         "to every report row; multi-model mode: the "
                         "isolation p99 ceiling attached to the "
                         "non-burst models in the burst row (what "
                         "perf_gate.py enforces)")
    ap.add_argument("--models", default="",
                    help="multi-model mode: ID=SHARE mix (e.g. "
                         "a=0.7,b=0.3) — every request carries a "
                         "'model' field drawn from this mix against a "
                         "serve.py --models pool")
    ap.add_argument("--burst-model", default="", dest="burst_model",
                    help="multi-model mode: the model whose requests "
                         "all fire back-to-back in the burst scenario "
                         "(default: first in the --models mix)")
    ap.add_argument("--throughput-floor", type=float, default=0.0,
                    dest="throughput_floor",
                    help="multi-model mode: attach this aggregate "
                         "imgs_per_sec floor to the mixed report row "
                         "(what perf_gate.py enforces)")
    ap.add_argument("--cascade", action="store_true",
                    help="cascade mode: the target serves with a "
                         "cascade router (serve.py --cascade) — run the "
                         "big_only baseline and gated cascade scenarios "
                         "over identical payloads and report "
                         "escalation_rate, per-class p99, and detection "
                         "agreement vs the big model")
    ap.add_argument("--speedup-floor", type=float, default=1.0,
                    dest="speedup_floor",
                    help="cascade mode: perf_gate floor on cascade "
                         "imgs_per_sec over big-only imgs_per_sec "
                         "(default 1.0 — the cascade must not lose to "
                         "always-big; 0 = no pin)")
    ap.add_argument("--agreement-floor", type=float, default=0.0,
                    dest="agreement_floor",
                    help="cascade mode: perf_gate floor on mean "
                         "detection agreement between the cascade's "
                         "answers and the big model's on the same "
                         "images (0 = no pin)")
    ap.add_argument("--watch-check", action="store_true",
                    dest="watch_check",
                    help="scrape the target's /alerts after the run and "
                         "assert the alert set matches expectations: "
                         "with no --watch-expect nothing may have fired "
                         "at all (the clean-traffic contract — a "
                         "fire-then-resolve during a steady run is "
                         "still an SLO breach); each --watch-expect "
                         "NAME must have fired (firing now or resolved "
                         "in the history), and nothing outside the "
                         "expected set may still be firing.  Exit 1 "
                         "with the mismatch on stderr; an 'alerts' "
                         "block joins each scenario summary")
    ap.add_argument("--watch-expect", action="append", default=[],
                    dest="watch_expect", metavar="NAME",
                    help="--watch-check: this alertname must have fired "
                         "by the end of the run (repeatable)")
    ap.add_argument("--trace-sample", type=float, default=0.0,
                    dest="trace_sample",
                    help="fraction of requests that carry a client-minted"
                         " distributed-trace id in the 'trace' doc field "
                         "(seeded); the server must echo it back — a "
                         "mismatch fails the run.  Output lines and "
                         "--report rows gain traced / tail_kept counts")
    return ap.parse_args(argv)


def parse_model_mix(spec):
    """``a=0.7,b=0.3`` → ordered ``[(id, normalized_share), ...]``."""
    mix = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        mid, eq, share = part.partition("=")
        if not mid or not eq:
            raise SystemExit(f"loadgen: bad --models entry {part!r} "
                             "(want ID=SHARE)")
        try:
            val = float(share)
        except ValueError:
            raise SystemExit(f"loadgen: bad --models share {share!r}")
        if val <= 0:
            raise SystemExit(f"loadgen: --models share for {mid!r} must "
                             "be positive")
        if any(m == mid for m, _ in mix):
            raise SystemExit(f"loadgen: duplicate model {mid!r}")
        mix.append((mid, val))
    if not mix:
        raise SystemExit("loadgen: --models given but empty")
    total = sum(v for _, v in mix)
    return [(m, v / total) for m, v in mix]


def make_payloads(args, seed=None, size_mix=False):
    rng = np.random.RandomState(args.seed if seed is None else seed)
    docs = []
    for i in range(args.n):
        h, w = ((args.short, args.long_) if i % 2 == 0
                else (args.long_, args.short))
        if size_mix:
            # adversarial mix: anywhere from tiny thumbnails up to the
            # full size, orientation re-flipped at random
            h = int(rng.randint(16, max(h, 17)))
            w = int(rng.randint(16, max(w, 17)))
        else:
            dh, dw = rng.randint(0, max(min(h, w) // 4, 1), 2)
            h, w = max(h - dh, 16), max(w - dw, 16)
        img = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
        doc = encode_image_payload(img)
        if args.deadline_ms > 0:
            doc["deadline_ms"] = args.deadline_ms
        if (getattr(args, "trace_sample", 0.0) > 0
                and rng.random_sample() < args.trace_sample):
            # client-minted trace id (bare 32-hex = root context); the
            # server echoes it under "trace" in the response
            doc["trace"] = rng.bytes(16).hex()
        docs.append(doc)
    return docs


def schedule(scenario, n, rate, burst=8):
    """Fire offsets (seconds from t0) for ``n`` requests.  All profiles
    hold the same AVERAGE rate so their reports compare; they differ only
    in arrival clumping."""
    if rate <= 0:
        return [0.0] * n
    if scenario == "bursty":
        burst = max(int(burst), 1)
        return [(i // burst) * (burst / rate) for i in range(n)]
    return [i / rate for i in range(n)]  # steady / size-mix


def profile_schedule(profile, n, rate):
    """Fire offsets for a time-varying profile (``PROFILE_SEGMENTS``),
    plus the serialized segment schedule ``[{requests, rate, t0_s}, …]``
    that goes into the report row — the run is reproducible from the doc
    alone.  Unlike :func:`schedule`, profiles deliberately VARY the
    rate: the shape is the test."""
    fracs = PROFILE_SEGMENTS[profile]
    offsets, segments = [], []
    t = 0.0
    remaining = n
    for i, (frac, mult) in enumerate(fracs):
        k = remaining if i == len(fracs) - 1 \
            else min(int(round(n * frac)), remaining)
        seg_rate = rate * mult if rate > 0 else 0.0
        segments.append({"requests": k, "rate": round(seg_rate, 3),
                         "t0_s": round(t, 3)})
        for j in range(k):
            offsets.append(t + (j / seg_rate if seg_rate > 0 else 0.0))
        if k and seg_rate > 0:
            t = offsets[-1] + 1.0 / seg_rate
        remaining -= k
        if remaining <= 0:
            break
    return offsets, segments


class FleetWatcher:
    """Samples a fabric router's ready-member count through ``/readyz``
    while a profile run is in flight — the member-count-vs-time series
    behind ``time_to_scale_s`` (how long the autoscaler took to grow the
    fleet after the load arrived) and the scale-up/drain-back story in
    the autoscale report."""

    def __init__(self, host, port, poll_s=0.3):
        self.host, self.port = host, port
        self.poll_s = max(float(poll_s), 0.05)
        self.samples = []  # (t_rel_s, ready_members)
        self._stop = threading.Event()
        self._thread = None

    def _sample(self):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=5.0)
        try:
            conn.request("GET", "/readyz")
            doc = json.loads(conn.getresponse().read())
            return int(doc.get("ready_members", 0))
        except (OSError, ValueError):
            return None
        finally:
            conn.close()

    def start(self):
        t0 = time.monotonic()

        def run():
            while not self._stop.is_set():
                v = self._sample()
                if v is not None:
                    self.samples.append(
                        (round(time.monotonic() - t0, 3), v))
                self._stop.wait(self.poll_s)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="fleet-watcher")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def report(self):
        """``{start, peak, end, time_to_scale_s, samples}`` — ``None``
        time_to_scale_s means the fleet never grew past its starting
        size (a flat run, or the authority held)."""
        s = list(self.samples)
        if not s:
            return {}
        start = s[0][1]
        tts = next((t for t, v in s if v > start), None)
        return {"start": start, "peak": max(v for _, v in s),
                "end": s[-1][1],
                "time_to_scale_s": tts,
                "samples": s}


def fabric_engine_recompiles(host, port, timeout=10.0):
    """``member → engine 'recompiles' counter`` from a fabric router's
    ``/metrics`` engines fold — diffed around a profile run (common
    members only) for the report's zero-recompile-during-scale assert."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", "/metrics")
        doc = json.loads(conn.getresponse().read())
    except (OSError, ValueError):
        return {}
    finally:
        conn.close()
    engines = doc.get("engines", {})
    out = {}
    for name, e in engines.items():
        if isinstance(e, dict):
            out[name] = int((e.get("counters") or {})
                            .get("recompiles", 0) or 0)
    return out


def tcp_request(host, port, doc, timeout):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/predict", body=json.dumps(doc).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def fabric_member_requests(host, port, timeout=10.0):
    """``member name → cumulative routed-request count`` from a fabric
    router's ``/metrics``; ``{}`` when the endpoint is unreachable or not
    a fabric router (a mid-chaos snapshot must not kill the run)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        doc = json.loads(resp.read())
    except (OSError, ValueError):
        return {}
    finally:
        conn.close()
    members = doc.get("fabric", {}).get("members", {})
    return {name: m.get("requests", 0) for name, m in members.items()
            if isinstance(m, dict)}


def fold_flywheel_sections(doc):
    """Fold a ``/metrics`` doc's flywheel stats into one
    ``{"captured", "sample_every"}`` view.  A single engine carries a
    top-level ``flywheel`` section; a fabric router instead folds member
    metrics under ``engines``, so fleet capture sums ``captured`` across
    members (``sample_every`` is the max — the most conservative
    expected-capture divisor).  ``{}`` when nothing captures."""
    fw = doc.get("flywheel")
    if isinstance(fw, dict):
        return {"captured": int(fw.get("captured", 0)),
                "sample_every": max(int(fw.get("sample_every", 1)), 1)}
    captured, sample_every, found = 0, 1, False
    engines = doc.get("engines")
    if isinstance(engines, dict):
        for m in engines.values():
            sub = m.get("flywheel") if isinstance(m, dict) else None
            if isinstance(sub, dict):
                found = True
                captured += int(sub.get("captured", 0))
                sample_every = max(sample_every,
                                   int(sub.get("sample_every", 1)))
    if not found:
        return {}
    return {"captured": captured, "sample_every": sample_every}


def flywheel_capture_stats(args, timeout=10.0):
    """``{"captured": n, "sample_every": k}`` from the target server's
    ``/metrics`` flywheel section (TCP or Unix socket) — folded across
    fabric members when the target is a router; ``{}`` when the
    endpoint is unreachable or capture is not enabled there."""
    try:
        if args.unix_socket:
            status, doc = unix_http_request(args.unix_socket, "GET",
                                            "/metrics", timeout=timeout)
        else:
            conn = http.client.HTTPConnection(args.host, args.port,
                                              timeout=timeout)
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                status, doc = resp.status, json.loads(resp.read())
            finally:
                conn.close()
    except (OSError, ValueError):
        return {}
    if status != 200 or not isinstance(doc, dict):
        return {}
    return fold_flywheel_sections(doc)


def trace_stats(args, timeout=10.0):
    """``{"spans_emitted": n, "tail_kept": k}`` from the target's
    ``/metrics`` trace section (engine server or fabric router); ``{}``
    when the endpoint is unreachable or tracing is off there."""
    try:
        if args.unix_socket:
            status, doc = unix_http_request(args.unix_socket, "GET",
                                            "/metrics", timeout=timeout)
        else:
            conn = http.client.HTTPConnection(args.host, args.port,
                                              timeout=timeout)
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                status, doc = resp.status, json.loads(resp.read())
            finally:
                conn.close()
    except (OSError, ValueError):
        return {}
    if status != 200 or not isinstance(doc, dict):
        return {}
    tr = doc.get("trace")
    if not isinstance(tr, dict):
        return {}
    return {k: int(tr.get(k, 0))
            for k in ("spans_emitted", "tail_kept")}


def watch_alerts_doc(args, timeout=10.0):
    """The target's ``/alerts`` document (a serve.py --watch process),
    ``{}`` when the route is absent (watchtower off there) or the
    target is unreachable."""
    try:
        if args.unix_socket:
            status, doc = unix_http_request(args.unix_socket, "GET",
                                            "/alerts", timeout=timeout)
        else:
            conn = http.client.HTTPConnection(args.host, args.port,
                                              timeout=timeout)
            try:
                conn.request("GET", "/alerts")
                resp = conn.getresponse()
                status, doc = resp.status, json.loads(resp.read())
            finally:
                conn.close()
    except (OSError, ValueError):
        return {}
    return doc if status == 200 and isinstance(doc, dict) else {}


def watch_alert_names(doc):
    """``(firing_names, fired_names)`` from an ``/alerts`` doc — fired
    covers both currently-firing and already-resolved instances (and
    silenced ones that reached the firing state: a silence hides the
    page, not the fact)."""
    firing = sorted({a.get("alert", "?")
                     for a in (doc.get("firing") or [])})
    fired = sorted({a.get("alert", "?")
                    for a in (doc.get("firing") or [])
                    + (doc.get("resolved") or [])
                    + [a for a in (doc.get("silenced") or [])
                       if a.get("state") == "firing"]})
    return firing, fired


def watch_check_failure(doc, expected):
    """None when the target's alert state matches ``expected`` (the
    --watch-expect alertnames), else the stderr failure line.  No
    expectations ⇒ the clean-traffic contract: nothing may have fired
    at all.  With expectations: every named alert must have fired, and
    nothing OUTSIDE the expected set may still be firing (a leftover
    firing alert means the injected fault never cleared).  A target
    with no /alerts route fails loudly — pointing --watch-check at a
    watch-off server is itself a smoke-script bug."""
    if not doc:
        return ("loadgen: --watch-check failed: target exposes no "
                "/alerts route (serve.py --watch not active?)")
    firing, fired = watch_alert_names(doc)
    if not expected:
        if fired:
            return (f"loadgen: --watch-check failed: expected a clean "
                    f"pass but {fired} fired (still firing: "
                    f"{firing or '[]'})")
        return None
    missing = sorted(set(expected) - set(fired))
    if missing:
        return (f"loadgen: --watch-check failed: expected {missing} to "
                f"fire; fired: {fired or '[]'}")
    stray = sorted(set(firing) - set(expected))
    if stray:
        return (f"loadgen: --watch-check failed: {stray} still firing "
                f"beyond the expected set {sorted(set(expected))}")
    return None


def trace_echo_failure(results):
    """None when every echoed trace id matched what was sent, else the
    stderr failure line (run_requests records mismatches as errors on
    otherwise-2xx results)."""
    mism = sorted({r[3] for r in results
                   if r[3] and r[3].startswith("trace echo mismatch")})
    if not mism:
        return None
    return (f"loadgen: trace echo assertion failed "
            f"({len(mism)} distinct): {'; '.join(mism[:3])}")


def capture_check_failure(before, after, ok_submits, tolerance):
    """None when the server's captured-count delta matches
    ``ok_submits / sample_every`` within ``tolerance`` (relative, with
    ±1 absolute slack for stride phase), else the stderr failure line.
    Missing flywheel sections fail loudly — a smoke script passing
    ``--capture-check`` against a capture-off server is itself a bug."""
    if not after:
        return ("loadgen: --capture-check failed: target exposes no "
                "flywheel section on /metrics (capture not enabled?)")
    sample_every = after["sample_every"]
    delta = after["captured"] - (before.get("captured", 0) if before else 0)
    expected = ok_submits / sample_every
    slack = max(1.0, tolerance * expected)
    if abs(delta - expected) > slack:
        return (f"loadgen: --capture-check failed: captured delta {delta} "
                f"vs expected {expected:.1f} ({ok_submits} 2xx submits / "
                f"sample_every {sample_every}, tolerance ±{slack:.1f})")
    return None


def member_share(before: dict, after: dict) -> dict:
    """Per-member fraction of the requests routed between two snapshots
    (members that joined mid-window count from zero)."""
    deltas = {name: after[name] - before.get(name, 0) for name in after}
    total = sum(d for d in deltas.values() if d > 0)
    return {name: round(max(d, 0) / max(total, 1), 4)
            for name, d in sorted(deltas.items())}


def run_requests(args, docs, offsets):
    """Fire every payload at its offset (open loop); returns
    ``(results, wall_s)`` where results[i] is
    ``(status, latency_s, queue_wait_ms, error_str, t_done_s)`` —
    ``t_done_s`` is the completion instant relative to the run start,
    what the time-to-recover failover metric is computed from."""
    n = len(docs)
    results = [None] * n

    def fire(i):
        t0 = time.perf_counter()
        try:
            if args.unix_socket:
                status, resp = unix_http_request(
                    args.unix_socket, "POST", "/predict", docs[i],
                    timeout=args.timeout)
            else:
                status, resp = tcp_request(args.host, args.port, docs[i],
                                           args.timeout)
        except Exception as e:  # noqa: BLE001 — a dead server is a result
            results[i] = (0, time.perf_counter() - t0, None,
                          f"{type(e).__name__}: {e}",
                          time.perf_counter() - t_start)
            return
        err = None
        sent = docs[i].get("trace")
        if sent and 200 <= status < 300 and resp.get("trace") != sent:
            err = (f"trace echo mismatch: sent {sent}, got "
                   f"{resp.get('trace')!r}")
        results[i] = (status, time.perf_counter() - t0,
                      resp.get("queue_wait_ms"), err,
                      time.perf_counter() - t_start)

    t_start = time.perf_counter()
    threads = []
    for i in range(n):
        lag = t_start + offsets[i] - time.perf_counter()
        if lag > 0:  # open loop: fire on the clock, never on replies
            time.sleep(lag)
        th = threading.Thread(target=fire, args=(i,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return results, time.perf_counter() - t_start


def summarize(results, wall):
    n = len(results)
    status_counts = {}
    for r in results:
        status_counts[str(r[0])] = status_counts.get(str(r[0]), 0) + 1
    ok = [r for r in results if 200 <= r[0] < 300]
    lat_ms = np.asarray([r[1] for r in ok]) * 1e3
    qw = [r[2] for r in ok if r[2] is not None]
    # availability: 2xx over NON-SHED submits — 503s are deliberate
    # backpressure/degradation (the shed contract), not unavailability;
    # 5xx and transport errors (status 0) are
    non_shed = n - status_counts.get("503", 0)
    # time-to-recover: first hard failure (5xx/transport, NOT the shed
    # 503s — same exclusion as availability) → the next 2xx COMPLETION
    # after it; null when the run never hard-failed (or never
    # recovered) — the failover metric replica chaos runs gate on
    fail_ts = sorted(r[4] for r in results
                     if r[0] == 0 or (r[0] >= 500 and r[0] != 503))
    recover_s = None
    if fail_ts:
        after = [r[4] for r in ok if r[4] > fail_ts[0]]
        recover_s = round(min(after) - fail_ts[0], 3) if after else None
    out = {
        "requests": n,
        "status": dict(sorted(status_counts.items())),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3) if ok else None,
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3) if ok else None,
        "error_rate": round((n - len(ok)) / max(n, 1), 4),
        "availability": round(len(ok) / max(non_shed, 1), 4),
        "time_to_recover_s": recover_s,
        "mean_queue_wait_ms": (round(float(np.mean(qw)), 3) if qw else None),
        "imgs_per_sec": round(len(ok) / wall, 3) if wall > 0 else None,
        "wall_s": round(wall, 3),
    }
    errors = sorted({r[3] for r in results if r[3]})
    if errors:
        out["errors"] = errors[:5]
    return out


def assert_2xx_failure(results):
    """None when every response was 2xx, else the stderr line naming each
    offending status code and its count (0 = transport error)."""
    bad = {}
    for r in results:
        if not 200 <= r[0] < 300:
            bad[r[0]] = bad.get(r[0], 0) + 1
    if not bad:
        return None
    total = sum(bad.values())
    parts = ", ".join(
        f"{ct}x status {st}" if st else f"{ct}x transport error"
        for st, ct in sorted(bad.items()))
    errors = sorted({r[3] for r in results if r[3]})
    msg = (f"loadgen: --assert-2xx failed: {total}/{len(results)} "
           f"responses were not 2xx ({parts})")
    if errors:
        msg += f"; first errors: {'; '.join(errors[:3])}"
    return msg


# -- stream mode (ISSUE 14) ----------------------------------------------


class StreamConn:
    """One persistent keep-alive HTTP connection (TCP or Unix socket) —
    the per-stream transport.  A camera holds its connection open; a
    transport failure reconnects once, then reports status 0."""

    def __init__(self, args):
        self.args = args
        self.conn = None

    def _connect(self):
        a = self.args
        if a.unix_socket:
            sock_path, timeout = a.unix_socket, a.timeout

            class Conn(http.client.HTTPConnection):
                def __init__(self):
                    super().__init__("localhost", timeout=timeout)

                def connect(self):
                    import socket as _socket
                    self.sock = _socket.socket(_socket.AF_UNIX,
                                               _socket.SOCK_STREAM)
                    self.sock.settimeout(timeout)
                    self.sock.connect(sock_path)

            self.conn = Conn()
        else:
            self.conn = http.client.HTTPConnection(a.host, a.port,
                                                   timeout=a.timeout)

    def post_frame(self, doc):
        """One frame → (per-frame status, response doc).  The HTTP
        envelope is 200 whenever the body parsed; the status that matters
        is the per-line one inside the NDJSON reply."""
        body = (json.dumps(doc) + "\n").encode()
        for attempt in (0, 1):
            try:
                if self.conn is None:
                    self._connect()
                self.conn.request(
                    "POST", "/stream", body=body,
                    headers={"Content-Type": "application/x-ndjson"})
                resp = self.conn.getresponse()
                raw = resp.read()
                if resp.status != 200:
                    return resp.status, {}
                line = raw.decode().strip().splitlines()
                out = json.loads(line[-1]) if line else {}
                return int(out.get("status", 0)), out
            except (OSError, ValueError) as e:
                self.close()
                if attempt:
                    return 0, {"error": f"{type(e).__name__}: {e}"}
        return 0, {}

    def close(self):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None


def make_stream_frames(rng, motion, n, h, w, cut_every=8):
    """``n`` consecutive (h, w, 3) uint8 frames of one motion profile."""
    scene = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
    frames = []
    for i in range(n):
        if motion == "pan":
            # the whole scene translates: every pixel changes, mean
            # absolute delta is large — the gate must take the full path
            frames.append(np.roll(scene, 3 * (i + 1), axis=1))
        elif motion == "scene-cut":
            if i and i % max(cut_every, 1) == 0:
                scene = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
            frames.append(scene.copy())
        else:  # static: ±1 sensor noise on ~5% of pixels
            f = scene.copy()
            k = max((h * w) // 20, 1)
            ys = rng.randint(0, h, k)
            xs = rng.randint(0, w, k)
            f[ys, xs] = np.clip(
                f[ys, xs].astype(np.int16)
                + rng.choice((-1, 1), (k, 1)), 0, 255).astype(np.uint8)
            frames.append(f)
    return frames


def server_metrics_doc(args, timeout=10.0):
    """The target's full ``/metrics`` doc (``{}`` when unreachable)."""
    try:
        if args.unix_socket:
            status, doc = unix_http_request(args.unix_socket, "GET",
                                            "/metrics", timeout=timeout)
        else:
            conn = http.client.HTTPConnection(args.host, args.port,
                                              timeout=timeout)
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                status, doc = resp.status, json.loads(resp.read())
            finally:
                conn.close()
    except (OSError, ValueError):
        return {}
    if status != 200 or not isinstance(doc, dict):
        return {}
    return doc


def server_counters(args, timeout=10.0):
    """The target's ``/metrics`` engine counters (``{}`` when
    unreachable) — diffed around a scenario for ``dispatches_per_frame``."""
    return server_metrics_doc(args, timeout=timeout).get("counters") or {}


def run_stream_scenario(args, motion, idx):
    """One motion profile: ``--streams`` concurrent closed-loop senders.
    Returns ``(per_stream_results, per_stream_dropped, wall_s)`` where
    results[s] is a list of ``(status, latency_s, skipped)``."""
    per_results = [[] for _ in range(args.streams)]
    per_dropped = [0] * args.streams
    interval = 1.0 / args.fps if args.fps > 0 else 0.0

    def run_one(si):
        rng = np.random.RandomState(args.seed + 1000 * idx + si)
        h, w = ((args.short, args.long_) if si % 2 == 0
                else (args.long_, args.short))
        frames = make_stream_frames(rng, motion, args.frames, h, w,
                                    cut_every=args.cut_every)
        conn = StreamConn(args)
        seq = 0
        t0 = time.perf_counter()
        for i, frame in enumerate(frames):
            target = t0 + i * interval
            now = time.perf_counter()
            if interval and now > target + interval:
                # more than a full slot late: a camera drops the frame
                # rather than queueing a stale one
                per_dropped[si] += 1
                continue
            if now < target:
                time.sleep(target - now)
            seq += 1
            doc = {"stream_id": f"{motion}-{si}", "seq": seq,
                   **encode_image_payload(frame)}
            if args.deadline_ms > 0:
                doc["deadline_ms"] = args.deadline_ms
            ts = time.perf_counter()
            status, resp = conn.post_frame(doc)
            per_results[si].append((status, time.perf_counter() - ts,
                                    bool(resp.get("skipped"))))
        conn.close()

    t_start = time.perf_counter()
    threads = [threading.Thread(target=run_one, args=(s,))
               for s in range(args.streams)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return per_results, per_dropped, time.perf_counter() - t_start


def summarize_streams(args, motion, per_results, per_dropped, wall):
    """One scenario's ``mxr_stream_report`` row.  ``p99_ms`` is the MAX
    over per-stream p99s — the SLO a fleet operator actually owes each
    camera — with the full per-stream list alongside."""
    flat = [r for rs in per_results for r in rs]
    status_counts = {}
    for r in flat:
        status_counts[str(r[0])] = status_counts.get(str(r[0]), 0) + 1
    ok = [r for r in flat if 200 <= r[0] < 300]
    per_stream_p99 = []
    for rs in per_results:
        lat = [r[1] for r in rs if 200 <= r[0] < 300]
        per_stream_p99.append(
            round(float(np.percentile(np.asarray(lat) * 1e3, 99)), 3)
            if lat else None)
    p99s = [p for p in per_stream_p99 if p is not None]
    all_lat = np.asarray([r[1] for r in ok]) * 1e3
    skipped = sum(1 for r in ok if r[2])
    return {
        "name": motion,
        "streams": args.streams,
        "fps": args.fps,
        "frames_per_stream": args.frames,
        "frames_sent": len(flat),
        "frames_dropped": sum(per_dropped),
        "status": dict(sorted(status_counts.items())),
        "p50_ms": (round(float(np.percentile(all_lat, 50)), 3)
                   if ok else None),
        "p99_ms": max(p99s) if p99s else None,
        "per_stream_p99_ms": per_stream_p99,
        "error_rate": round((len(flat) - len(ok)) / max(len(flat), 1), 4),
        "skip_fraction": round(skipped / max(len(ok), 1), 4),
        "imgs_per_sec": round(len(ok) / wall, 3) if wall > 0 else None,
        "wall_s": round(wall, 3),
    }


def stream_main(args):
    """Stream-mode driver: one scenario per ``--motion`` profile, one
    ``mxr_stream_report`` doc for the gate."""
    motions = args.motions or ["static"]
    rows = []
    all_status = []
    for idx, motion in enumerate(motions):
        before = server_counters(args, timeout=args.timeout)
        per_results, per_dropped, wall = run_stream_scenario(
            args, motion, idx)
        after = server_counters(args, timeout=args.timeout)
        row = summarize_streams(args, motion, per_results, per_dropped,
                                wall)
        if after and row["frames_sent"]:
            row["dispatches_per_frame"] = round(
                (after.get("dispatches", 0) - before.get("dispatches", 0))
                / row["frames_sent"], 4)
        if motion == "static" and args.skip_floor > 0:
            row["skip_fraction_floor"] = args.skip_floor
        if args.p99_ceiling_ms > 0:
            row["p99_ceiling_ms"] = args.p99_ceiling_ms
        rows.append(row)
        all_status.extend(r[0] for rs in per_results for r in rs)
        print(json.dumps({"scenario": motion, **row}))

    if args.report:
        doc = {"schema": STREAM_REPORT_SCHEMA, "version": REPORT_VERSION,
               "scenarios": rows}
        with open(args.report, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)

    if args.assert_2xx:
        bad = [s for s in all_status if not 200 <= s < 300]
        if bad:
            counts = {}
            for s in bad:
                counts[s] = counts.get(s, 0) + 1
            parts = ", ".join(
                f"{ct}x status {st}" if st else f"{ct}x transport error"
                for st, ct in sorted(counts.items()))
            print(f"loadgen: --assert-2xx failed: {len(bad)}/"
                  f"{len(all_status)} frames were not 2xx ({parts})",
                  file=sys.stderr)
            sys.exit(1)


# -- multi-model mode (ISSUE 15) ------------------------------------------


MM_MODEL_KEYS = ("requests", "status", "p50_ms", "p99_ms", "error_rate",
                 "availability", "mean_queue_wait_ms")


def assign_models(mix, n, rng):
    """Model id per request slot: a seeded weighted draw, then a
    guarantee that every model in the mix appears at least once (a tiny
    ``--n`` must still exercise every tenant)."""
    ids = [m for m, _ in mix]
    shares = np.asarray([s for _, s in mix])
    picks = [ids[i] for i in rng.choice(len(ids), size=n, p=shares)]
    for j, mid in enumerate(ids):
        if n > j and mid not in picks:
            picks[j] = mid
    return picks


def multimodel_offsets(scenario, picks, burst_model, n, rate):
    """Fire offsets for the multi-model profiles.  ``mixed`` is plain
    steady.  ``burst``: non-burst models keep their steady slots while
    every burst-model request fires at one instant a quarter into the
    window — the sibling models' latency THROUGH that spike is the
    isolation measurement."""
    steady = schedule("steady", n, rate)
    if scenario != "burst" or rate <= 0:
        return steady
    burst_at = steady[-1] * 0.25
    return [burst_at if picks[i] == burst_model else steady[i]
            for i in range(n)]


def summarize_per_model(picks, results, wall):
    """``model id → per-model summary block`` (the fields the
    MULTIMODEL gate scores), in mix order of first appearance."""
    out = {}
    for mid in dict.fromkeys(picks):
        sub = [r for p, r in zip(picks, results) if p == mid]
        summ = summarize(sub, wall)
        out[mid] = {k: summ[k] for k in MM_MODEL_KEYS if k in summ}
    return out


def multimodel_main(args):
    """Multi-model driver: the ``mixed`` (aggregate throughput) and
    ``burst`` (tenant isolation) scenarios against one model pool; one
    ``mxr_multimodel_report`` doc for the gate."""
    mix = parse_model_mix(args.models)
    burst_model = args.burst_model or mix[0][0]
    if burst_model not in (m for m, _ in mix):
        raise SystemExit(f"loadgen: --burst-model {burst_model!r} not "
                         "in the --models mix")
    rows = []
    all_results = []
    for idx, scenario in enumerate(("mixed", "burst")):
        docs = make_payloads(args, seed=args.seed + idx)
        rng = np.random.RandomState(args.seed + 7000 + idx)
        picks = assign_models(mix, args.n, rng)
        for doc, mid in zip(docs, picks):
            doc["model"] = mid
        offsets = multimodel_offsets(scenario, picks, burst_model,
                                     args.n, args.rate)
        results, wall = run_requests(args, docs, offsets)
        all_results.extend(results)
        out = summarize(results, wall)
        out["models"] = summarize_per_model(picks, results, wall)
        row = {"name": scenario,
               "mix": {m: round(s, 4) for m, s in mix},
               **{k: v for k, v in out.items()
                  if k in ("requests", "status", "p50_ms", "p99_ms",
                           "error_rate", "availability", "imgs_per_sec",
                           "wall_s", "models")}}
        if scenario == "burst":
            row["burst_model"] = burst_model
            if args.p99_ceiling_ms > 0:
                row["isolation_p99_ceiling_ms"] = args.p99_ceiling_ms
        elif args.throughput_floor > 0:
            row["imgs_per_sec_floor"] = args.throughput_floor
        rows.append(row)
        print(json.dumps({"scenario": scenario, **out}))

    if args.report:
        doc = {"schema": MULTIMODEL_REPORT_SCHEMA,
               "version": REPORT_VERSION, "scenarios": rows}
        with open(args.report, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)

    if args.assert_2xx:
        msg = assert_2xx_failure(all_results)
        if msg is not None:
            print(msg, file=sys.stderr)
            sys.exit(1)


# -- cascade mode (ISSUE 19) ----------------------------------------------


def run_cascade_requests(args, docs, offsets):
    """:func:`run_requests` with the response doc RETAINED per result —
    results[i] is ``(status, latency_s, queue_wait_ms, error_str,
    t_done_s, response_doc)``.  Cascade mode needs the bodies: the
    ``cascade`` provenance field (escalated flag → per-class split) and
    the ``detections`` (→ agreement vs the big-only pass)."""
    n = len(docs)
    results = [None] * n

    def fire(i):
        t0 = time.perf_counter()
        try:
            if args.unix_socket:
                status, resp = unix_http_request(
                    args.unix_socket, "POST", "/predict", docs[i],
                    timeout=args.timeout)
            else:
                status, resp = tcp_request(args.host, args.port, docs[i],
                                           args.timeout)
        except Exception as e:  # noqa: BLE001 — a dead server is a result
            results[i] = (0, time.perf_counter() - t0, None,
                          f"{type(e).__name__}: {e}",
                          time.perf_counter() - t_start, {})
            return
        results[i] = (status, time.perf_counter() - t0,
                      resp.get("queue_wait_ms"), None,
                      time.perf_counter() - t_start, resp)

    t_start = time.perf_counter()
    threads = []
    for i in range(n):
        lag = t_start + offsets[i] - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        th = threading.Thread(target=fire, args=(i,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return results, time.perf_counter() - t_start


def latency_class_block(results):
    """p50/p99 over one escalation class of 6-tuple results (the
    per-class split the CASCADE gate trends)."""
    lat = np.asarray([r[1] for r in results
                      if 200 <= r[0] < 300]) * 1e3
    return {
        "requests": len(results),
        "p50_ms": (round(float(np.percentile(lat, 50)), 3)
                   if lat.size else None),
        "p99_ms": (round(float(np.percentile(lat, 99)), 3)
                   if lat.size else None),
    }


def cascade_agreement(cascade_results, big_results):
    """Mean :func:`detection_agreement` between the cascade's answers
    and the big model's over the SAME images (index-matched — both
    passes are built from the same seed), None when no pair completed.
    The big-only detections are the reference ("labels") side."""
    from mx_rcnn_tpu.flywheel.fleet import detection_agreement
    vals = []
    for c, b in zip(cascade_results, big_results):
        if not (200 <= c[0] < 300 and 200 <= b[0] < 300):
            continue
        vals.append(detection_agreement(c[5].get("detections") or [],
                                        b[5].get("detections") or []))
    return round(float(np.mean(vals)), 4) if vals else None


def cascade_main(args):
    """Cascade-mode driver: the ``big_only`` baseline then the gated
    ``cascade`` scenario over identical payloads; one
    ``mxr_cascade_report`` doc for the gate."""
    info = server_metrics_doc(args, timeout=args.timeout).get("cascade")
    if not isinstance(info, dict) or not info.get("big"):
        raise SystemExit("loadgen: --cascade target exposes no cascade "
                         "section on /metrics (serve.py --cascade not "
                         "active?)")
    small, big = info.get("small"), info["big"]
    offsets = schedule("steady", args.n, args.rate)
    keep = ("requests", "status", "p50_ms", "p99_ms", "error_rate",
            "availability", "imgs_per_sec", "wall_s")
    rows, all_results = [], []

    # baseline: the same images addressed straight at the big model —
    # what the cascade's throughput and answers are scored against
    docs = make_payloads(args, seed=args.seed)
    for doc in docs:
        doc["model"] = big
    big_results, big_wall = run_cascade_requests(args, docs, offsets)
    all_results.extend(r[:5] for r in big_results)
    big_out = summarize([r[:5] for r in big_results], big_wall)
    rows.append({"name": "big_only", "model": big,
                 **{k: v for k, v in big_out.items() if k in keep}})
    print(json.dumps({"scenario": "big_only", **big_out}))

    # the gated pass: identical payloads (same seed), default routing
    docs = make_payloads(args, seed=args.seed)
    before = dict(info.get("counters") or {})
    results, wall = run_cascade_requests(args, docs, offsets)
    after = server_metrics_doc(args, timeout=args.timeout).get("cascade")
    all_results.extend(r[:5] for r in results)
    out = summarize([r[:5] for r in results], wall)

    ok = [r for r in results if 200 <= r[0] < 300]
    esc = [r for r in ok if (r[5].get("cascade") or {}).get("escalated")]
    small_ans = [r for r in ok
                 if not (r[5].get("cascade") or {}).get("escalated")]
    out["escalation_rate"] = round(len(esc) / max(len(ok), 1), 4)
    out["classes"] = {"answered_small": latency_class_block(small_ans),
                      "escalated": latency_class_block(esc)}
    if isinstance(after, dict):
        # the server's own view of THIS run (counter delta), the
        # cross-check script/cascade_smoke.sh asserts against
        ac, bc = after.get("counters") or {}, before
        dec = ((ac.get("answered_small", 0) - bc.get("answered_small", 0))
               + (ac.get("escalated", 0) - bc.get("escalated", 0)))
        if dec > 0:
            out["server_escalation_rate"] = round(
                (ac.get("escalated", 0) - bc.get("escalated", 0)) / dec, 4)
    agree = cascade_agreement(results, big_results)
    out["agreement"] = agree
    big_ips = big_out.get("imgs_per_sec")
    if big_ips and out.get("imgs_per_sec"):
        out["big_only_imgs_per_sec"] = big_ips
        out["speedup_vs_big"] = round(out["imgs_per_sec"] / big_ips, 4)
    row = {"name": "cascade", "small": small, "big": big,
           "thresh": info.get("thresh"),
           **{k: v for k, v in out.items()
              if k in keep + ("escalation_rate", "server_escalation_rate",
                              "classes", "agreement",
                              "big_only_imgs_per_sec", "speedup_vs_big")}}
    if args.speedup_floor > 0:
        row["speedup_floor"] = args.speedup_floor
    if args.agreement_floor > 0:
        row["agreement_floor"] = args.agreement_floor
    if args.throughput_floor > 0:
        row["imgs_per_sec_floor"] = args.throughput_floor
    rows.append(row)
    print(json.dumps({"scenario": "cascade", **out}))

    if args.report:
        doc = {"schema": CASCADE_REPORT_SCHEMA, "version": REPORT_VERSION,
               "scenarios": rows}
        with open(args.report, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)

    if args.assert_2xx:
        msg = assert_2xx_failure(all_results)
        if msg is not None:
            print(msg, file=sys.stderr)
            sys.exit(1)


def main(argv=None):
    args = parse_args(argv)
    if bool(args.unix_socket) == bool(args.port):
        raise SystemExit("pass exactly one of --port / --unix-socket")
    if args.fabric and not args.port:
        raise SystemExit("--fabric needs a TCP router (--port)")
    if args.cascade:
        if args.models or args.streams > 0:
            raise SystemExit("--cascade is exclusive with --models / "
                             "--streams (the pair comes from the "
                             "server's /metrics)")
        return cascade_main(args)
    if args.models:
        if args.streams > 0:
            raise SystemExit("--models and --streams are exclusive")
        return multimodel_main(args)
    if args.streams > 0:
        return stream_main(args)

    scenarios = args.scenarios or [None]
    report_rows = []
    all_results = []
    capture_before = (flywheel_capture_stats(args, timeout=args.timeout)
                      if args.capture_check else None)
    for idx, scenario in enumerate(scenarios):
        docs = make_payloads(args, seed=args.seed + idx,
                             size_mix=(scenario == "size-mix"))
        segments = None
        if args.profile:
            offsets, segments = profile_schedule(args.profile, args.n,
                                                 args.rate)
        else:
            offsets = schedule(scenario or "steady", args.n, args.rate,
                               burst=args.burst)
        before = (fabric_member_requests(args.host, args.port,
                                         timeout=args.timeout)
                  if args.fabric else None)
        recompiles_before = (fabric_engine_recompiles(
            args.host, args.port, timeout=args.timeout)
            if args.fabric and args.profile else None)
        watcher = None
        if args.fabric and args.profile:
            watcher = FleetWatcher(args.host, args.port,
                                   poll_s=args.fleet_poll_s).start()
        results, wall = run_requests(args, docs, offsets)
        if watcher is not None:
            watcher.stop()
        all_results.extend(results)
        out = summarize(results, wall)
        if args.fabric:
            after = fabric_member_requests(args.host, args.port,
                                           timeout=args.timeout)
            out["member_share"] = member_share(before, after)
            out["fabric_members"] = len(after)
        if args.profile:
            out["profile"] = args.profile
            out["schedule"] = segments
            if watcher is not None:
                fleet = watcher.report()
                out["fleet"] = fleet
                out["time_to_scale_s"] = fleet.get("time_to_scale_s")
            if recompiles_before is not None:
                recompiles_after = fabric_engine_recompiles(
                    args.host, args.port, timeout=args.timeout)
                out["recompiles_during_run"] = sum(
                    recompiles_after[k] - recompiles_before[k]
                    for k in recompiles_after
                    if k in recompiles_before)
            # perf-gate pins for autoscale_report_rows()
            if args.p99_ceiling_ms > 0:
                out["p99_ceiling_ms"] = args.p99_ceiling_ms
            if args.scale_floor > 0:
                out["scale_floor"] = args.scale_floor
            if args.time_to_scale_ceiling_s > 0:
                out["time_to_scale_ceiling_s"] = \
                    args.time_to_scale_ceiling_s
            out["recompile_ceiling"] = 0.0
        if args.trace_sample > 0:
            out["traced"] = sum(1 for d in docs if "trace" in d)
            out["tail_kept"] = trace_stats(
                args, timeout=args.timeout).get("tail_kept")
        if args.watch_check:
            wdoc = watch_alerts_doc(args, timeout=args.timeout)
            firing, fired = watch_alert_names(wdoc)
            out["alerts"] = ({"firing": firing, "fired": fired,
                              "ticks": wdoc.get("ticks")}
                             if wdoc else None)
        if scenario is not None:
            out = {"scenario": scenario, **out}
        if scenario is not None or args.report:
            report_rows.append({"name": scenario or "default", **{
                k: v for k, v in out.items()
                if k in ("requests", "status", "p50_ms", "p99_ms",
                         "error_rate", "availability", "time_to_recover_s",
                         "imgs_per_sec", "wall_s", "member_share",
                         "fabric_members", "traced", "tail_kept",
                         "profile", "schedule", "fleet", "time_to_scale_s",
                         "recompiles_during_run", "p99_ceiling_ms",
                         "scale_floor", "time_to_scale_ceiling_s",
                         "recompile_ceiling", "alerts")}})
        print(json.dumps(out))

    if args.report:
        schema = AUTOSCALE_REPORT_SCHEMA if args.profile else REPORT_SCHEMA
        doc = {"schema": schema, "version": REPORT_VERSION,
               "scenarios": report_rows}
        with open(args.report, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)

    if args.capture_check:
        after = flywheel_capture_stats(args, timeout=args.timeout)
        ok = sum(1 for r in all_results if 200 <= r[0] < 300)
        msg = capture_check_failure(capture_before, after, ok,
                                    args.capture_tolerance)
        if msg is not None:
            print(msg, file=sys.stderr)
            sys.exit(1)

    if args.trace_sample > 0:
        msg = trace_echo_failure(all_results)
        if msg is not None:
            print(msg, file=sys.stderr)
            sys.exit(1)

    if args.watch_check:
        msg = watch_check_failure(
            watch_alerts_doc(args, timeout=args.timeout),
            args.watch_expect)
        if msg is not None:
            print(msg, file=sys.stderr)
            sys.exit(1)

    if args.assert_2xx:
        msg = assert_2xx_failure(all_results)
        if msg is not None:
            print(msg, file=sys.stderr)
            sys.exit(1)


if __name__ == "__main__":
    main()
