#!/usr/bin/env python
"""Open-loop HTTP load generator for serve.py — latency under load.

  python scripts/loadgen.py --host 127.0.0.1 --port 8321 --n 64 --rate 20
  python scripts/loadgen.py --unix-socket /tmp/serve.sock --n 32 --rate 0

Open-loop: request k is FIRED at its scheduled instant k/rate regardless
of whether earlier responses came back (each request gets its own
thread), so a slow server accumulates in-flight work and the latency
distribution shows it — closed-loop generators that wait for responses
throttle themselves to the server's pace and hide exactly the queueing
behavior this exists to measure (the coordinated-omission trap).
``--rate 0`` fires everything at once (burst mode: what backpressure
tests want).

Bodies are mixed-size random uint8 images — half landscape, half
portrait, dimensions jittered per request (seeded) — so the server
exercises both orientation buckets and real ``resize_to_bucket`` work.

Prints exactly ONE JSON line:

  {"requests": N, "status": {"200": k, "503": m, ...}, "p50_ms": ...,
   "p99_ms": ..., "mean_queue_wait_ms": ..., "imgs_per_sec": ...,
   "wall_s": ...}

latency percentiles are over 2xx responses (client-observed, including
queue wait + forward + post-process + transport); ``imgs_per_sec`` is
2xx responses over the wall from first fire to last response.  With
``--assert-2xx`` the exit code is 1 unless every response was 2xx —
what script/serve_smoke.sh runs.  Pure stdlib + numpy; no jax import,
safe on a machine with no accelerator.
"""

import argparse
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.serve.frontend import (encode_image_payload,  # noqa: E402
                                        unix_http_request)


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--unix-socket", default="", dest="unix_socket",
                    help="target a Unix-socket server instead of TCP")
    ap.add_argument("--n", type=int, default=32, help="requests to fire")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="arrival rate, req/s (0 = fire all at once)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    dest="deadline_ms",
                    help="per-request deadline forwarded to the server "
                         "(0 = server default)")
    ap.add_argument("--short", type=int, default=480,
                    help="short side of generated images (long side is "
                         "--long); pick at or under the server's bucket "
                         "scale")
    ap.add_argument("--long", type=int, default=640, dest="long_")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-request client wait")
    ap.add_argument("--assert-2xx", action="store_true", dest="assert_2xx",
                    help="exit 1 unless every response was 2xx")
    return ap.parse_args()


def make_payloads(args):
    rng = np.random.RandomState(args.seed)
    docs = []
    for i in range(args.n):
        h, w = ((args.short, args.long_) if i % 2 == 0
                else (args.long_, args.short))
        dh, dw = rng.randint(0, max(min(h, w) // 4, 1), 2)
        img = rng.randint(0, 255, (max(h - dh, 16), max(w - dw, 16), 3),
                          dtype=np.uint8)
        doc = encode_image_payload(img)
        if args.deadline_ms > 0:
            doc["deadline_ms"] = args.deadline_ms
        docs.append(doc)
    return docs


def tcp_request(host, port, doc, timeout):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/predict", body=json.dumps(doc).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def main():
    args = parse_args()
    if bool(args.unix_socket) == bool(args.port):
        raise SystemExit("pass exactly one of --port / --unix-socket")
    docs = make_payloads(args)

    results = [None] * args.n  # (status, latency_s, queue_wait_ms)

    def fire(i):
        t0 = time.perf_counter()
        try:
            if args.unix_socket:
                status, resp = unix_http_request(
                    args.unix_socket, "POST", "/predict", docs[i],
                    timeout=args.timeout)
            else:
                status, resp = tcp_request(args.host, args.port, docs[i],
                                           args.timeout)
        except Exception as e:  # noqa: BLE001 — a dead server is a result
            results[i] = (0, time.perf_counter() - t0, None,
                          f"{type(e).__name__}: {e}")
            return
        results[i] = (status, time.perf_counter() - t0,
                      resp.get("queue_wait_ms"), None)

    t_start = time.perf_counter()
    threads = []
    for i in range(args.n):
        if args.rate > 0:  # open loop: fire on the clock, never on replies
            lag = t_start + i / args.rate - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
        th = threading.Thread(target=fire, args=(i,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    wall = time.perf_counter() - t_start

    status_counts = {}
    for st, _, _, _ in results:
        status_counts[str(st)] = status_counts.get(str(st), 0) + 1
    ok = [r for r in results if 200 <= r[0] < 300]
    lat_ms = np.asarray([r[1] for r in ok]) * 1e3
    qw = [r[2] for r in ok if r[2] is not None]
    out = {
        "requests": args.n,
        "status": dict(sorted(status_counts.items())),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3) if ok else None,
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3) if ok else None,
        "mean_queue_wait_ms": (round(float(np.mean(qw)), 3) if qw else None),
        "imgs_per_sec": round(len(ok) / wall, 3),
        "wall_s": round(wall, 3),
    }
    errors = sorted({r[3] for r in results if r[3]})
    if errors:
        out["errors"] = errors[:5]
    print(json.dumps(out))
    if args.assert_2xx and len(ok) != args.n:
        sys.exit(1)


if __name__ == "__main__":
    main()
