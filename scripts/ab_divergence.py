#!/usr/bin/env python
"""Numeric-divergence A/B ledger (VERDICT round-2 item 3).

Measures the fixture-mAP cost of every deliberate numeric divergence from
the reference's f32 CUDA semantics (`roi_pooling.cu`, MXNet symbol graph),
by running the REAL CLIs (train_end2end.py -> test.py) over the on-disk
mini-VOC fixture on the attached TPU chip, once per config variant:

  base       bf16 backbone, ROI_SAMPLING_RATIO=1, avg pooling, f32
             momentum (the shipped classic config — f32 momentum is the
             default again after the round-3 advisor pointed out fixture
             neutrality cannot bound a real-dataset regression)
  f32_body   tpu__COMPUTE_DTYPE=\"float32\"       — the bf16-backbone divergence
  sr2        tpu__ROI_SAMPLING_RATIO=2        — the 1-sample RoIAlign tradeoff
  sr2_max    sr2 + tpu__ROI_MODE=\"max\"          — bilinear-max (closest to the
             reference's max-reduction ROIPooling) vs avg at the same grid
  bf16_mom   TRAIN__OPT_ACC_DTYPE=\"bfloat16\"    — the opt-in bf16 momentum
             storage (measures the divergence the opt-in would introduce)

Each variant trains the same 6 epochs / seed on 2007_trainval (16 imgs,
flip->32) and evals held-out 2007_minitest.  Output: one table row per
variant with fixture-class mean AP and delta vs base, pasted into
BASELINE.md's divergence ledger.

Fixture-scale caveat (stated in the ledger too): mini-VOC is 3 classes of
colored rectangles — a divergence that costs nothing here can still cost
on VOC07/COCO; these numbers bound the *mechanical* regression (broken
gradients, rounding collapse), not paper-parity mAP.
"""

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np

from fixtures import FIXTURE_CLASSES, make_mini_voc
from test_cli_integration import TINY_TEST, TINY_TRAIN, run_cli

VARIANTS = {
    "base": [],
    # seed replicas of base: the fixture's run-to-run noise band — a
    # variant's delta only means something outside this band (6-epoch
    # from-scratch training is chaotic; round-3 measured base spanning
    # 0.30-0.53 across configs whose math should be near-identical)
    "base_s1": ["--seed", "1"],
    "base_s2": ["--seed", "2"],
    "f32_body": ["--cfg", "tpu__COMPUTE_DTYPE=\"float32\""],
    "sr2": ["--cfg", "tpu__ROI_SAMPLING_RATIO=2"],
    "sr2_max": ["--cfg", "tpu__ROI_SAMPLING_RATIO=2",
                "--cfg", "tpu__ROI_MODE=\"max\""],
    "bf16_mom": ["--cfg", "TRAIN__OPT_ACC_DTYPE=\"bfloat16\""],
    # round-4: bf16 storage of the RPN assign IoU matrix (the FPN-floor
    # lever — threshold-marginal anchors may flip label)
    "bf16_iou": ["--cfg", "TRAIN__RPN_ASSIGN_IOU_BF16=True"],
}


def run_variant(name, extra, work):
    root = os.path.join(work, name)
    shutil.rmtree(root, ignore_errors=True)
    voc = os.path.join(work, "VOCdevkit")  # fixture shared across variants
    common = ["--network", "resnet50", "--dataset", "PascalVOC",
              "--root_path", os.path.join(root, "data"),
              "--dataset_path", voc,
              "--prefix", os.path.join(root, "model", "e2e"),
              "--devices", "1"]
    # --seed is a train-only flag; config overrides go to both CLIs
    test_extra = [a for i, a in enumerate(extra)
                  if a != "--seed" and (i == 0 or extra[i - 1] != "--seed")]
    run_cli("train_end2end", common + [
        "--image_set", "2007_trainval", "--end_epoch", "6",
        "--batch_images", "2", "--lr", "0.005", "--frequent", "8",
    ] + TINY_TRAIN + extra)
    stats = run_cli("test", common + [
        "--image_set", "2007_minitest", "--epoch", "6",
    ] + TINY_TEST + test_extra)
    return float(np.mean([stats[c] for c in FIXTURE_CLASSES]))


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/ab_divergence"
    only = sys.argv[2].split(",") if len(sys.argv) > 2 else list(VARIANTS)
    voc = os.path.join(work, "VOCdevkit")
    if not os.path.isdir(voc):
        make_mini_voc(voc)
    results = {}
    for name in only:
        results[name] = run_variant(name, VARIANTS[name], work)
        print(f"[ab] {name}: fixture mAP {results[name]:.4f}", flush=True)
    base = results.get("base")
    print(json.dumps(results))
    if base is not None:
        print(f"{'variant':10s} {'mAP':>7s} {'delta':>8s}")
        for k, v in results.items():
            print(f"{k:10s} {v:7.4f} {v - base:+8.4f}")


if __name__ == "__main__":
    main()
