#!/usr/bin/env python
"""Regression gate over the BENCH_*.json trajectory.

  python scripts/perf_gate.py                     # gate BENCH_r*.json in .
  python scripts/perf_gate.py --dir runs --threshold 0.15
  python scripts/perf_gate.py --check-format BENCH_r*.json BENCH_BASELINE.json

Prints a per-metric trend table and exits nonzero when the NEWEST
``vs_baseline`` regresses more than ``--threshold`` (default 10%) below
the best prior run of the same metric.  Rows with
``baseline_recorded: true`` carry a null ratio by design (the run
recorded the baseline it would have compared against — PR-4's
null-baseline fix) and are skipped, as is any row without a numeric
``vs_baseline``.

Comparisons never cross ``baseline_method``: BENCH_BASELINE.json holds
one baseline per dispatch method (staged ``value`` vs chain
``value_chain``), so a chain-method 1.0 ratio right after a cross-method
14x is a method switch, not a 14x regression.  Rows without the field
(the pre-fix trajectory) form their own group.

``--check-format`` only validates that every file parses and every
extracted row has ``metric``/``value``/``unit`` and a numeric-or-null
``vs_baseline`` — script/obs_smoke.sh wires it over the checked-in
trajectory.  Pure stdlib/host-side JSON: no jax import.
"""

import argparse
import glob
import json
import os
import sys

GATE_THRESHOLD = 0.10


def load_rows(path: str) -> list:
    """Extract metric rows from one trajectory artifact.  Shapes seen in
    the wild: the driver's ``{"n", "cmd", "rc", "tail", "parsed"}`` wrapper
    (``parsed`` = the last bench JSON line), a bare bench output line, and
    BENCH_BASELINE.json (``metric``/``value`` but no ``vs_baseline`` —
    it IS the baseline)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return [doc["parsed"]]
    if isinstance(doc, dict) and "metric" in doc:
        return [doc]
    return []


def check_format(paths: list) -> list:
    """Format errors (empty when every file is a valid trajectory row)."""
    errors = []
    for path in paths:
        try:
            rows = load_rows(path)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable ({e})")
            continue
        if not rows:
            errors.append(f"{path}: no metric row found (expected "
                          f"'parsed' or top-level 'metric')")
            continue
        for row in rows:
            for field in ("metric", "value"):
                if field not in row:
                    errors.append(f"{path}: row missing '{field}'")
            if not isinstance(row.get("value", 0.0), (int, float)):
                errors.append(f"{path}: 'value' not a number: "
                              f"{row.get('value')!r}")
            vs = row.get("vs_baseline", None)
            if vs is not None and not isinstance(vs, (int, float)):
                errors.append(f"{path}: 'vs_baseline' neither numeric "
                              f"nor null: {vs!r}")
    return errors


def build_series(paths: list) -> dict:
    """``(metric, baseline_method) → [(file, row)]`` in file order (the
    BENCH_rNN naming sorts chronologically)."""
    series: dict = {}
    for path in paths:
        for row in load_rows(path):
            if "vs_baseline" not in row:
                continue  # BENCH_BASELINE.json: not a trajectory point
            key = (row.get("metric", "?"), row.get("baseline_method"))
            series.setdefault(key, []).append((path, row))
    return series


def gate(series: dict, threshold: float = GATE_THRESHOLD) -> list:
    """The failures: newest scored run > threshold below the best prior
    scored run of the same (metric, baseline_method)."""
    failures = []
    for (metric, method), hist in sorted(
            series.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")):
        scored = [(p, r["vs_baseline"]) for p, r in hist
                  if isinstance(r.get("vs_baseline"), (int, float))
                  and not r.get("baseline_recorded")]
        if len(scored) < 2:
            continue
        newest_path, newest = scored[-1]
        best_prior = max(v for _, v in scored[:-1])
        if newest < best_prior * (1.0 - threshold):
            failures.append(
                f"{metric}"
                + (f" [{method}]" if method else "")
                + f": newest vs_baseline {newest:g} "
                f"({os.path.basename(newest_path)}) is "
                f"{(1 - newest / best_prior) * 100:.1f}% below the best "
                f"prior {best_prior:g}")
    return failures


def trend_table(series: dict) -> str:
    lines = []
    for (metric, method), hist in sorted(
            series.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")):
        label = metric + (f" [{method}]" if method else "")
        lines.append(label)
        for path, row in hist:
            vs = row.get("vs_baseline")
            note = ""
            if row.get("baseline_recorded"):
                note = "  (baseline recorded this run — not scored)"
            lines.append(
                f"  {os.path.basename(path):<24} value="
                f"{row.get('value', float('nan')):>10.3f} "
                f"{row.get('unit', ''):<9} vs_baseline="
                f"{'null' if vs is None else f'{vs:g}'}{note}")
    return "\n".join(lines) if lines else "(no trajectory rows)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="*",
                    help="trajectory files (default: --dir/BENCH_r*.json)")
    ap.add_argument("--dir", default=".",
                    help="where to glob BENCH_r*.json when no paths given")
    ap.add_argument("--threshold", type=float, default=GATE_THRESHOLD,
                    help="allowed fractional drop vs the best prior run "
                         "(default 0.10)")
    ap.add_argument("--check-format", action="store_true",
                    dest="check_format",
                    help="only validate the files parse as trajectory "
                         "rows; no gating")
    args = ap.parse_args(argv)

    paths = args.paths or sorted(glob.glob(
        os.path.join(args.dir, "BENCH_r*.json")))
    if not paths:
        print("perf_gate: no BENCH_*.json files found", file=sys.stderr)
        return 2

    if args.check_format:
        errors = check_format(paths)
        for e in errors:
            print(f"perf_gate: FORMAT {e}", file=sys.stderr)
        if not errors:
            print(f"perf_gate: {len(paths)} file(s) well-formed")
        return 1 if errors else 0

    series = build_series(paths)
    print(trend_table(series))
    failures = gate(series, args.threshold)
    for f in failures:
        print(f"perf_gate: REGRESSION {f}", file=sys.stderr)
    if not failures:
        print(f"perf_gate: OK ({len(paths)} run(s), threshold "
              f"{args.threshold * 100:.0f}%)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
