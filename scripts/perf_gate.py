#!/usr/bin/env python
"""Regression gate over the BENCH_*.json + SLO_*.json trajectory.

  python scripts/perf_gate.py                # gate BENCH_r*/SLO_r*.json in .
  python scripts/perf_gate.py --dir runs --threshold 0.15
  python scripts/perf_gate.py --check-format BENCH_r*.json SLO_r*.json

Prints a per-metric trend table and exits nonzero when the NEWEST run
regresses more than ``--threshold`` (default 10%) against the prior
trajectory of the same metric.  Two row dialects:

* **throughput rows** (bench): higher is better, scored on the
  ``vs_baseline`` ratio — newest must not fall more than the threshold
  below the best prior.  Rows with ``baseline_recorded: true`` carry a
  null ratio by design (the run recorded the baseline it would have
  compared against — PR-4's null-baseline fix) and are skipped, as is
  any row without a numeric ``vs_baseline``.
* **latency/error rows** (``"direction": "down"`` — what an
  ``mxr_slo_report`` from ``scripts/loadgen.py --report`` expands to):
  lower is better, scored on the RAW value — newest must not exceed the
  best (lowest) prior by more than the threshold.  ``abs_slack`` on a
  row (error_rate uses 0.02) adds an absolute allowance so a best prior
  of exactly 0 doesn't make any nonzero newest value a failure.  This is
  the gate that stops "fast but drops bursts" from merging: p50/p99 and
  error-rate per loadgen scenario are scored alongside imgs/sec.

Two absolute dialects score the NEWEST run alone (properties, not
trends): ``floor`` rows fail below their bound (replica linearity,
flywheel loop closure, the streaming skip_fraction), ``ceiling`` rows
fail above it (the per-stream p99 SLO an ``mxr_stream_report`` pins
via ``--p99-ceiling-ms``).

Comparisons never cross ``baseline_method``: BENCH_BASELINE.json holds
one baseline per dispatch method (staged ``value`` vs chain
``value_chain``), so a chain-method 1.0 ratio right after a cross-method
14x is a method switch, not a 14x regression.  Rows without the field
(the pre-fix trajectory) form their own group.

``--check-format`` only validates that every file parses and every
extracted row has ``metric``/``value``/``unit`` and a numeric-or-null
``vs_baseline`` — script/obs_smoke.sh and script/slo_smoke.sh wire it
over the checked-in trajectory.  Pure stdlib/host-side JSON: no jax
import.
"""

import argparse
import glob
import json
import os
import sys

GATE_THRESHOLD = 0.10
# absolute slack for error-rate rows: a prior trajectory of 0.0 errors
# would otherwise turn ANY nonzero newest rate into a failure — allow up
# to 2 percentage points of noise before the relative threshold applies
ERROR_RATE_ABS_SLACK = 0.02
# serve-bench startup rows (cold_start_s / warmup_compile_s from
# bench.py --mode serve) expand into direction=down rows with a couple
# of seconds of absolute slack — process startup shares the machine
# with whatever else CI runs, and sub-second jitter on a warm-cache
# boot must not read as a lost AOT warm start
STARTUP_ABS_SLACK_S = 2.0
# multi-replica linearity floor: aggregate imgs/sec must reach at least
# this fraction of per-replica × N on the CPU smoke — below it the
# router/supervisor overhead (or accidental serialization) is eating
# the replication win
REPLICA_LINEARITY_FLOOR = 0.85
# cross-host fabric (mxr_fabric_report): same linearity property over N
# TCP members behind the fabric router, plus the partition floor —
# while a member is partitioned away the reachable subset must still
# answer at least this 2xx fraction of non-shed requests
FABRIC_LINEARITY_FLOOR = 0.85
FABRIC_PARTITION_AVAILABILITY_FLOOR = 0.90
# overlapped-eval floor: the pipelined pred_eval must at least match the
# serial loop on the same box (speedup ratio >= 1.0) — a pipeline that
# loses to serial means the overlap machinery is pure overhead
EVAL_SPEEDUP_FLOOR = 1.0
# readback accounting (serve bench): bytes per image crossing device→host
# is a property of the program contract, not the box — near-zero absolute
# slack, so a fused path silently regressing to fat readbacks fails even
# when wall-clock hides it on CPU.  host_prep_ms shares the startup slack
# (submit-thread timing is scheduler-noisy on a shared CI box).
READBACK_ABS_SLACK_BYTES = 1024.0
HOST_PREP_ABS_SLACK_MS = 2.0
# data-flywheel loop closure (mxr_flywheel_report): the smoke must mine
# SOME nonzero fraction of what it captured, and the replica must have
# hot-reloaded at least one replay-trained checkpoint generation
FLYWHEEL_MINED_FRACTION_FLOOR = 0.01
FLYWHEEL_GENERATION_FLOOR = 1.0
# fleet mode (FLYWHEEL_r02+): under injected chaos the loop must still
# promote at least one generation — a silently-stalled flywheel fails
# the gate instead of shipping
FLYWHEEL_PROMOTED_FLOOR = 1.0
# streaming (mxr_stream_report + the serve-bench stream fields):
# dispatches_per_frame is a counter ratio, not wall-clock, but batch
# fill still varies with thread scheduling — allow a quarter-dispatch
# of absolute noise before the relative threshold applies.  The bench's
# static-profile skip_fraction floor is far below what the gate
# actually achieves (~0.9 with max_skip=16 over 32 frames) so only a
# broken gate trips it — the BENCH_r08 lesson: new metric families get
# their own series and conservative first thresholds.
STREAM_DPF_ABS_SLACK = 0.25
BENCH_SKIP_FRACTION_FLOOR = 0.5
# cascade serving (mxr_cascade_report): the cascade must not LOSE to
# always-big on the same box — imgs/sec over the big-only baseline run
# floors at 1.0 unless the run pinned its own — and the answers must
# agree with the big model's (mean detection_agreement floor; the run
# pins the value, there is no universal default because it depends on
# how far apart the two checkpoints are)
CASCADE_SPEEDUP_FLOOR = 1.0
# time_to_scale is dominated by the autoscaler's tick interval and the
# member readiness probe cadence, both sub-second in the smoke — a
# second of absolute noise before the relative trend threshold applies.
TIME_TO_SCALE_ABS_SLACK = 1.0


def slo_report_rows(doc: dict) -> list:
    """Expand an ``mxr_slo_report`` into direction-aware metric rows —
    one p50/p99/error_rate triple per scenario (null values, e.g. a
    scenario with zero 2xx responses, are dropped; the error_rate row
    still scores it)."""
    rows = []
    for sc in doc.get("scenarios", []):
        name = sc.get("name", "?")
        for field, unit, slack in (("p50_ms", "ms", 0.0),
                                   ("p99_ms", "ms", 0.0),
                                   ("error_rate", "fraction",
                                    ERROR_RATE_ABS_SLACK)):
            v = sc.get(field)
            if not isinstance(v, (int, float)):
                continue
            row = {"metric": f"slo_{name}_{field}", "value": v,
                   "unit": unit, "direction": "down"}
            if slack:
                row["abs_slack"] = slack
            rows.append(row)
        # distributed-tracing ride-alongs (loadgen --trace-sample):
        # counts scale with --n so they are validated (--check-format),
        # not trend-gated; a run may pin "traced_floor" to make "the
        # client minted ids but none were echoed/counted" a hard failure
        traced = sc.get("traced")
        if isinstance(traced, (int, float)):
            row = {"metric": f"slo_{name}_traced", "value": traced,
                   "unit": "requests"}
            floor = sc.get("traced_floor")
            if isinstance(floor, (int, float)):
                row["floor"] = floor
            rows.append(row)
        tail = sc.get("tail_kept")
        if isinstance(tail, (int, float)):
            rows.append({"metric": f"slo_{name}_tail_kept",
                         "value": tail, "unit": "traces"})
    return rows


def replica_report_rows(doc: dict) -> list:
    """Expand an ``mxr_replica_report`` (script/replica_smoke.sh) into
    FLOOR rows: scored against an absolute minimum on the newest run
    alone — replication linearity is a property, not a trend, so a
    single run can (and must) fail on its own."""
    rows = []
    n = doc.get("replicas")
    agg = doc.get("aggregate_imgs_per_sec")
    per = doc.get("per_replica_imgs_per_sec")
    if (isinstance(n, int) and n > 0
            and isinstance(agg, (int, float))
            and isinstance(per, (int, float)) and per > 0):
        rows.append({"metric": "replica_linearity",
                     "value": round(agg / (per * n), 4),
                     "unit": "fraction",
                     "floor": doc.get("linearity_floor",
                                      REPLICA_LINEARITY_FLOOR)})
    avail = doc.get("availability")
    if isinstance(avail, (int, float)):
        floor = doc.get("availability_floor")
        row = {"metric": "replica_availability", "value": avail,
               "unit": "fraction"}
        if isinstance(floor, (int, float)):
            row["floor"] = floor
        rows.append(row)
    return rows


def fabric_report_rows(doc: dict) -> list:
    """Expand an ``mxr_fabric_report`` (script/fabric_smoke.sh) into
    FLOOR rows, the replica-report dialect generalized to remote TCP
    members: linearity of aggregate throughput across N members, chaos
    availability, and — the fabric-specific property — availability
    while a member is partitioned away."""
    rows = []
    n = doc.get("members")
    agg = doc.get("aggregate_imgs_per_sec")
    per = doc.get("per_member_imgs_per_sec")
    if (isinstance(n, int) and n > 0
            and isinstance(agg, (int, float))
            and isinstance(per, (int, float)) and per > 0):
        rows.append({"metric": "fabric_linearity",
                     "value": round(agg / (per * n), 4),
                     "unit": "fraction",
                     "floor": doc.get("linearity_floor",
                                      FABRIC_LINEARITY_FLOOR)})
    avail = doc.get("availability")
    if isinstance(avail, (int, float)):
        row = {"metric": "fabric_availability", "value": avail,
               "unit": "fraction"}
        floor = doc.get("availability_floor")
        if isinstance(floor, (int, float)):
            row["floor"] = floor
        rows.append(row)
    part = doc.get("availability_under_partition")
    if isinstance(part, (int, float)):
        rows.append({"metric": "fabric_partition_availability",
                     "value": part, "unit": "fraction",
                     "floor": doc.get("partition_availability_floor",
                                      FABRIC_PARTITION_AVAILABILITY_FLOOR)})
    return rows


def flywheel_report_rows(doc: dict) -> list:
    """Expand an ``mxr_flywheel_report`` (script/flywheel_smoke.sh) into
    FLOOR rows — loop closure is a property of the build, scored on the
    newest run alone: some fraction of the captured traffic must have
    mined into the replay manifest, and the serving generation must have
    advanced when the replay-trained checkpoint hot-reloaded."""
    rows = []
    captured = doc.get("captured")
    mined = doc.get("mined")
    if (isinstance(captured, (int, float)) and captured > 0
            and isinstance(mined, (int, float))):
        rows.append({"metric": "flywheel_mined_fraction",
                     "value": round(mined / captured, 4),
                     "unit": "fraction",
                     "floor": doc.get("mined_fraction_floor",
                                      FLYWHEEL_MINED_FRACTION_FLOOR)})
    before = doc.get("generation_before")
    after = doc.get("generation_after")
    if isinstance(before, (int, float)) and isinstance(after, (int, float)):
        rows.append({"metric": "flywheel_reload_generations",
                     "value": float(after - before),
                     "unit": "generations",
                     "floor": doc.get("generation_floor",
                                      FLYWHEEL_GENERATION_FLOOR)})
    # fleet-mode fields (FLYWHEEL_r02+) are strictly additive: absent in
    # an r01 report, so its rows — and the r01 gate verdict — are
    # untouched.  generation_promoted is the chaos-certification FLOOR;
    # the gate/drift tallies ride along ungated for trend visibility.
    promoted = doc.get("generation_promoted")
    if isinstance(promoted, (int, float)):
        rows.append({"metric": "flywheel_generation_promoted",
                     "value": float(promoted),
                     "unit": "generations",
                     "floor": doc.get("promoted_floor",
                                      FLYWHEEL_PROMOTED_FLOOR)})
    for field, metric in (("promotion_gate_pass",
                           "flywheel_promotion_gate_pass"),
                          ("drift_detected",
                           "flywheel_drift_detected")):
        val = doc.get(field)
        if isinstance(val, (int, float)):
            rows.append({"metric": metric, "value": float(val),
                         "unit": "count"})
    return rows


def stream_report_rows(doc: dict) -> list:
    """Expand an ``mxr_stream_report`` (scripts/loadgen.py --streams)
    into rows — per motion profile: per-stream p99 (a CEILING row when
    the run pinned ``p99_ceiling_ms``, scored on the newest run alone
    like a floor; a direction=down trend row otherwise), error_rate,
    ``dispatches_per_frame`` (direction=down: the coalescing/skip win
    must not erode), and — when the run pinned ``skip_fraction_floor``
    (the static profile) — a skip_fraction FLOOR row."""
    rows = []
    for sc in doc.get("scenarios", []):
        name = sc.get("name", "?")
        p99 = sc.get("p99_ms")
        if isinstance(p99, (int, float)):
            row = {"metric": f"stream_{name}_p99_ms", "value": p99,
                   "unit": "ms", "direction": "down"}
            ceil = sc.get("p99_ceiling_ms")
            if isinstance(ceil, (int, float)) and ceil > 0:
                row = {"metric": f"stream_{name}_p99_ms", "value": p99,
                       "unit": "ms", "ceiling": ceil}
            rows.append(row)
        er = sc.get("error_rate")
        if isinstance(er, (int, float)):
            rows.append({"metric": f"stream_{name}_error_rate",
                         "value": er, "unit": "fraction",
                         "direction": "down",
                         "abs_slack": ERROR_RATE_ABS_SLACK})
        dpf = sc.get("dispatches_per_frame")
        if isinstance(dpf, (int, float)):
            rows.append({"metric": f"stream_{name}_dispatches_per_frame",
                         "value": dpf, "unit": "ratio",
                         "direction": "down",
                         "abs_slack": STREAM_DPF_ABS_SLACK})
        floor = sc.get("skip_fraction_floor")
        sf = sc.get("skip_fraction")
        if (isinstance(floor, (int, float)) and floor > 0
                and isinstance(sf, (int, float))):
            rows.append({"metric": f"stream_{name}_skip_fraction",
                         "value": sf, "unit": "fraction", "floor": floor})
    return rows


def multimodel_report_rows(doc: dict) -> list:
    """Expand an ``mxr_multimodel_report`` (scripts/loadgen.py --models)
    into rows.  The two ISSUE-15 properties score the newest run alone:
    the ``mixed`` scenario's aggregate ``imgs_per_sec`` against the
    FLOOR the run pinned (``--throughput-floor`` — the pool must not
    cost aggregate throughput vs a single-model baseline), and in the
    ``burst`` scenario every NON-burst model's p99 against the
    isolation CEILING (``--p99-ceiling-ms`` — one tenant's burst must
    not blow a sibling's SLO).  Aggregate and per-model p50/p99/
    error_rate ride along as direction=down trend rows."""
    rows = []
    for sc in doc.get("scenarios", []):
        name = sc.get("name", "?")
        for field, unit, slack in (("p50_ms", "ms", 0.0),
                                   ("p99_ms", "ms", 0.0),
                                   ("error_rate", "fraction",
                                    ERROR_RATE_ABS_SLACK)):
            v = sc.get(field)
            if not isinstance(v, (int, float)):
                continue
            row = {"metric": f"mm_{name}_{field}", "value": v,
                   "unit": unit, "direction": "down"}
            if slack:
                row["abs_slack"] = slack
            rows.append(row)
        floor = sc.get("imgs_per_sec_floor")
        tput = sc.get("imgs_per_sec")
        if (isinstance(floor, (int, float)) and floor > 0
                and isinstance(tput, (int, float))):
            rows.append({"metric": f"mm_{name}_imgs_per_sec",
                         "value": tput, "unit": "imgs/s", "floor": floor})
        burst_model = sc.get("burst_model")
        ceil = sc.get("isolation_p99_ceiling_ms")
        for mid, m in sorted((sc.get("models") or {}).items()):
            if not isinstance(m, dict):
                continue
            p99 = m.get("p99_ms")
            if isinstance(p99, (int, float)):
                row = {"metric": f"mm_{name}_{mid}_p99_ms", "value": p99,
                       "unit": "ms", "direction": "down"}
                if (isinstance(ceil, (int, float)) and ceil > 0
                        and mid != burst_model):
                    # the isolation property: a sibling's p99 THROUGH
                    # the burst, scored absolutely on this run alone
                    row = {"metric": f"mm_{name}_{mid}_p99_ms",
                           "value": p99, "unit": "ms", "ceiling": ceil}
                rows.append(row)
            er = m.get("error_rate")
            if isinstance(er, (int, float)):
                rows.append({"metric": f"mm_{name}_{mid}_error_rate",
                             "value": er, "unit": "fraction",
                             "direction": "down",
                             "abs_slack": ERROR_RATE_ABS_SLACK})
    return rows


def autoscale_report_rows(doc: dict) -> list:
    """Expand an ``mxr_autoscale_report`` (scripts/loadgen.py --profile,
    script/autoscale_smoke.sh) into rows.  The ISSUE-18 properties score
    the newest run alone: p99 through the scale events against the
    CEILING the run pinned (``--p99-ceiling-ms`` — scaling must not blow
    the SLO while it happens), fleet growth (peak − start) against the
    ``scale_floor`` FLOOR (the authority must actually have scaled up
    under the flash crowd), ``time_to_scale_s`` against its pinned
    ceiling (a direction=down trend row otherwise), and
    ``recompiles_during_run`` against a zero CEILING — elastic capacity
    must come from the shared AOT cache, never from fresh XLA compiles.
    A top-level ``fleet_excess_recompiles`` (injected by the smoke from
    per-member registry counters: aot_miss beyond warmup) gets the same
    zero-ceiling treatment."""
    rows = []
    for sc in doc.get("scenarios", []):
        name = sc.get("name", "?")
        p99 = sc.get("p99_ms")
        if isinstance(p99, (int, float)):
            row = {"metric": f"autoscale_{name}_p99_ms", "value": p99,
                   "unit": "ms", "direction": "down"}
            ceil = sc.get("p99_ceiling_ms")
            if isinstance(ceil, (int, float)) and ceil > 0:
                row = {"metric": f"autoscale_{name}_p99_ms", "value": p99,
                       "unit": "ms", "ceiling": ceil}
            rows.append(row)
        er = sc.get("error_rate")
        if isinstance(er, (int, float)):
            rows.append({"metric": f"autoscale_{name}_error_rate",
                         "value": er, "unit": "fraction",
                         "direction": "down",
                         "abs_slack": ERROR_RATE_ABS_SLACK})
        fleet = sc.get("fleet") or {}
        floor = sc.get("scale_floor")
        if (isinstance(floor, (int, float)) and floor > 0
                and isinstance(fleet.get("peak"), (int, float))
                and isinstance(fleet.get("start"), (int, float))):
            rows.append({"metric": f"autoscale_{name}_scale_up",
                         "value": float(fleet["peak"] - fleet["start"]),
                         "unit": "members", "floor": floor})
        tts = sc.get("time_to_scale_s")
        if isinstance(tts, (int, float)):
            row = {"metric": f"autoscale_{name}_time_to_scale_s",
                   "value": tts, "unit": "s", "direction": "down",
                   "abs_slack": TIME_TO_SCALE_ABS_SLACK}
            ceil = sc.get("time_to_scale_ceiling_s")
            if isinstance(ceil, (int, float)) and ceil > 0:
                row = {"metric": f"autoscale_{name}_time_to_scale_s",
                       "value": tts, "unit": "s", "ceiling": ceil}
            rows.append(row)
        rec = sc.get("recompiles_during_run")
        if isinstance(rec, (int, float)):
            rows.append({"metric": f"autoscale_{name}_recompiles",
                         "value": float(rec), "unit": "programs",
                         "ceiling": float(
                             sc.get("recompile_ceiling") or 0.0)})
    excess = doc.get("fleet_excess_recompiles")
    if isinstance(excess, (int, float)):
        rows.append({"metric": "autoscale_fleet_excess_recompiles",
                     "value": float(excess), "unit": "programs",
                     "ceiling": float(doc.get("recompile_ceiling")
                                      or 0.0)})
    return rows


def cascade_report_rows(doc: dict) -> list:
    """Expand an ``mxr_cascade_report`` (scripts/loadgen.py --cascade,
    script/cascade_smoke.sh) into rows.  The ISSUE-19 properties score
    the newest run alone: ``speedup_vs_big`` — cascade imgs/sec over the
    big-only baseline measured in the SAME run — against its FLOOR
    (default 1.0: a cascade that loses to always-big is pure overhead),
    and mean ``agreement`` vs the big model's answers against the floor
    the run pinned (``--agreement-floor``).  An absolute imgs_per_sec
    floor rides when pinned.  Aggregate and per-class (answered_small /
    escalated) p50/p99 and error_rate trend as direction=down rows;
    ``escalation_rate`` is validated but not gated — its live (0, 1)
    assertion belongs to the smoke script, and its healthy value is a
    property of the traffic, not the build."""
    rows = []
    for sc in doc.get("scenarios", []):
        name = sc.get("name", "?")
        for field, unit, slack in (("p50_ms", "ms", 0.0),
                                   ("p99_ms", "ms", 0.0),
                                   ("error_rate", "fraction",
                                    ERROR_RATE_ABS_SLACK)):
            v = sc.get(field)
            if not isinstance(v, (int, float)):
                continue
            row = {"metric": f"cascade_{name}_{field}", "value": v,
                   "unit": unit, "direction": "down"}
            if slack:
                row["abs_slack"] = slack
            rows.append(row)
        for cls, block in sorted((sc.get("classes") or {}).items()):
            if not isinstance(block, dict):
                continue
            for field in ("p50_ms", "p99_ms"):
                v = block.get(field)
                if isinstance(v, (int, float)):
                    rows.append({"metric": f"cascade_{cls}_{field}",
                                 "value": v, "unit": "ms",
                                 "direction": "down"})
        sp = sc.get("speedup_vs_big")
        if isinstance(sp, (int, float)):
            rows.append({"metric": "cascade_speedup_vs_big",
                         "value": round(float(sp), 4), "unit": "ratio",
                         "floor": sc.get("speedup_floor",
                                         CASCADE_SPEEDUP_FLOOR)})
        agree = sc.get("agreement")
        if isinstance(agree, (int, float)):
            row = {"metric": "cascade_agreement", "value": agree,
                   "unit": "fraction"}
            floor = sc.get("agreement_floor")
            if isinstance(floor, (int, float)) and floor > 0:
                row["floor"] = floor
            rows.append(row)
        floor = sc.get("imgs_per_sec_floor")
        tput = sc.get("imgs_per_sec")
        if (isinstance(floor, (int, float)) and floor > 0
                and isinstance(tput, (int, float))
                and name == "cascade"):
            rows.append({"metric": "cascade_imgs_per_sec",
                         "value": tput, "unit": "imgs/s", "floor": floor})
        er = sc.get("escalation_rate")
        if isinstance(er, (int, float)):
            rows.append({"metric": f"cascade_{name}_escalation_rate",
                         "value": er, "unit": "fraction"})
    return rows


def watch_report_rows(doc: dict) -> list:
    """Expand an ``mxr_watch_report`` (script/watch_smoke.sh) into rows.
    The ISSUE-20 properties are all absolute, scored on the newest run
    alone: the clean-traffic pass must fire NOTHING (ceiling 0), the
    fault phase must actually fire and then resolve (floors — an alert
    pipeline that misses an injected SLO burn is worse than none), a
    firing alert must have carried trace ids into its flight dump, and
    nothing may still be firing when the run ends (ceiling 0 — a stuck
    alert is a broken lifecycle, not a noisy one).  rule_errors gets a
    zero ceiling: the default pack must evaluate cleanly every tick."""
    rows = []
    for field, metric, dialect, default in (
            ("clean_fired", "watch_clean_fired", "ceiling", 0.0),
            ("firing_at_end", "watch_firing_at_end", "ceiling", 0.0),
            ("rule_errors", "watch_rule_errors", "ceiling", 0.0),
            ("fault_fired", "watch_fault_fired", "floor", 1.0),
            ("fault_resolved", "watch_fault_resolved", "floor", 1.0),
            ("fault_trace_ids", "watch_fault_trace_ids", "floor", 1.0)):
        v = doc.get(field)
        if isinstance(v, (int, float)):
            bound = doc.get(f"{field}_{dialect}", default)
            rows.append({"metric": metric, "value": float(v),
                         "unit": "alerts", dialect: float(bound)})
    transitions = doc.get("transitions")
    if isinstance(transitions, (int, float)):
        # validated ride-along: total transition volume scales with run
        # length, so it trends informationally rather than gating
        rows.append({"metric": "watch_transitions",
                     "value": float(transitions), "unit": "transitions"})
    return rows


def load_rows(path: str) -> list:
    """Extract metric rows from one trajectory artifact.  Shapes seen in
    the wild: the driver's ``{"n", "cmd", "rc", "tail", "parsed"}`` wrapper
    (``parsed`` = the last bench JSON line), a bare bench output line,
    BENCH_BASELINE.json (``metric``/``value`` but no ``vs_baseline`` —
    it IS the baseline), and loadgen's ``mxr_slo_report`` (expanded into
    lower-is-better rows per scenario)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_slo_report":
        return slo_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_replica_report":
        return replica_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_fabric_report":
        return fabric_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_flywheel_report":
        return flywheel_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_stream_report":
        return stream_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_multimodel_report":
        return multimodel_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_autoscale_report":
        return autoscale_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_cascade_report":
        return cascade_report_rows(doc)
    if isinstance(doc, dict) and doc.get("schema") == "mxr_watch_report":
        return watch_report_rows(doc)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return startup_rows([doc["parsed"]])
    if isinstance(doc, dict) and "metric" in doc:
        return startup_rows([doc])
    return []


def startup_rows(rows: list) -> list:
    """Expand a bench row's auxiliary fields into rows of their own:
    ``cold_start_s`` / ``warmup_compile_s`` (serve bench) become
    lower-is-better rows, so the AOT warm-start win is gated exactly like
    a latency metric — a run that regresses to cold-compiling at boot
    fails, not just one that serves slowly; an ``eval`` sub-dict
    (bench.py --mode eval) contributes a ``speedup_vs_serial`` FLOOR row
    scored on the newest run alone — "pipelined beats serial on the same
    box" is a property of the build, not a trend."""
    out = list(rows)
    for row in rows:
        for field in ("cold_start_s", "warmup_compile_s"):
            v = row.get(field)
            if isinstance(v, (int, float)):
                out.append({"metric": f"{row.get('metric', '?')}_{field}",
                            "value": v, "unit": "s", "direction": "down",
                            "abs_slack": STARTUP_ABS_SLACK_S})
        # serve-bench boundary accounting (direction=down like the startup
        # rows; keyed by the parent metric, so _e2e and unfused rows are
        # separate series and never score against each other)
        for field, unit, slack in (
                ("readback_bytes_per_image", "bytes",
                 READBACK_ABS_SLACK_BYTES),
                ("host_prep_ms", "ms", HOST_PREP_ABS_SLACK_MS)):
            v = row.get(field)
            if isinstance(v, (int, float)):
                out.append({"metric": f"{row.get('metric', '?')}_{field}",
                            "value": v, "unit": unit, "direction": "down",
                            "abs_slack": slack})
        # serve-bench stream phase (bench.py --serve-stream): coalescing
        # and skip wins as their own series keyed by the parent metric —
        # never scored against the request/response throughput rows
        v = row.get("dispatches_per_frame")
        if isinstance(v, (int, float)):
            out.append({"metric":
                        f"{row.get('metric', '?')}_dispatches_per_frame",
                        "value": v, "unit": "ratio", "direction": "down",
                        "abs_slack": STREAM_DPF_ABS_SLACK})
        v = row.get("skip_fraction")
        if isinstance(v, (int, float)):
            out.append({"metric": f"{row.get('metric', '?')}_skip_fraction",
                        "value": v, "unit": "fraction",
                        "floor": row.get("skip_fraction_floor",
                                         BENCH_SKIP_FRACTION_FLOOR)})
        # cascade phase (bench.py --serve-cascade): escalation_rate rides
        # keyed by the cascade metric — validated, not trend-gated (its
        # healthy value is a property of the traffic, not the build)
        v = row.get("escalation_rate")
        if isinstance(v, (int, float)):
            out.append({"metric":
                        f"{row.get('metric', '?')}_escalation_rate",
                        "value": v, "unit": "fraction"})
        ev = row.get("eval")
        if isinstance(ev, dict):
            sp = ev.get("speedup_vs_serial")
            if isinstance(sp, (int, float)):
                out.append({"metric": "eval_pipeline_speedup",
                            "value": round(float(sp), 4), "unit": "ratio",
                            "floor": ev.get("speedup_floor",
                                            EVAL_SPEEDUP_FLOOR)})
    return out


def check_format(paths: list) -> list:
    """Format errors (empty when every file is a valid trajectory row)."""
    errors = []
    for path in paths:
        try:
            rows = load_rows(path)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable ({e})")
            continue
        if not rows:
            errors.append(f"{path}: no metric row found (expected "
                          f"'parsed', top-level 'metric', or an "
                          f"mxr_slo_report with scenarios)")
            continue
        for row in rows:
            for field in ("metric", "value"):
                if field not in row:
                    errors.append(f"{path}: row missing '{field}'")
            if not isinstance(row.get("value", 0.0), (int, float)):
                errors.append(f"{path}: 'value' not a number: "
                              f"{row.get('value')!r}")
            vs = row.get("vs_baseline", None)
            if vs is not None and not isinstance(vs, (int, float)):
                errors.append(f"{path}: 'vs_baseline' neither numeric "
                              f"nor null: {vs!r}")
    return errors


def build_series(paths: list) -> dict:
    """``(metric, baseline_method) → [(file, row)]`` in file order (the
    BENCH_rNN naming sorts chronologically)."""
    series: dict = {}
    for path in paths:
        for row in load_rows(path):
            if ("vs_baseline" not in row and row.get("direction") != "down"
                    and "floor" not in row and "ceiling" not in row):
                continue  # BENCH_BASELINE.json: not a trajectory point
            key = (row.get("metric", "?"), row.get("baseline_method"))
            series.setdefault(key, []).append((path, row))
    return series


def gate(series: dict, threshold: float = GATE_THRESHOLD) -> list:
    """The failures: newest scored run > threshold below the best prior
    scored run of the same (metric, baseline_method)."""
    failures = []
    for (metric, method), hist in sorted(
            series.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")):
        if any("floor" in r for _, r in hist):
            # absolute floor (replica linearity/availability): the newest
            # run is scored alone — no prior trajectory needed, a single
            # sub-floor run fails
            newest_path, newest_row = hist[-1]
            v, floor = newest_row.get("value"), newest_row.get("floor")
            if (isinstance(v, (int, float))
                    and isinstance(floor, (int, float)) and v < floor):
                failures.append(
                    f"{metric}: value {v:g} "
                    f"({os.path.basename(newest_path)}) is below the "
                    f"required floor {floor:g}")
            continue
        if any("ceiling" in r for _, r in hist):
            # absolute ceiling (per-stream p99 SLO): the floor dialect
            # mirrored — newest run scored alone, fails when ABOVE
            newest_path, newest_row = hist[-1]
            v, ceil = newest_row.get("value"), newest_row.get("ceiling")
            if (isinstance(v, (int, float))
                    and isinstance(ceil, (int, float)) and v > ceil):
                failures.append(
                    f"{metric}: value {v:g} "
                    f"({os.path.basename(newest_path)}) exceeds the "
                    f"required ceiling {ceil:g}")
            continue
        if any(r.get("direction") == "down" for _, r in hist):
            # lower-is-better: score the raw value against the best
            # (lowest) prior, with any per-row absolute slack added
            scored = [(p, r) for p, r in hist
                      if isinstance(r.get("value"), (int, float))]
            if len(scored) < 2:
                continue
            newest_path, newest_row = scored[-1]
            newest = newest_row["value"]
            best_prior = min(r["value"] for _, r in scored[:-1])
            slack = max((r.get("abs_slack", 0.0) for _, r in scored),
                        default=0.0)
            limit = best_prior * (1.0 + threshold) + slack
            if newest > limit:
                failures.append(
                    f"{metric}: newest value {newest:g} "
                    f"({os.path.basename(newest_path)}) exceeds the best "
                    f"prior {best_prior:g} by more than "
                    f"{threshold * 100:.0f}%"
                    + (f" (+{slack:g} slack)" if slack else ""))
            continue
        scored = [(p, r["vs_baseline"]) for p, r in hist
                  if isinstance(r.get("vs_baseline"), (int, float))
                  and not r.get("baseline_recorded")]
        if len(scored) < 2:
            continue
        newest_path, newest = scored[-1]
        best_prior = max(v for _, v in scored[:-1])
        if newest < best_prior * (1.0 - threshold):
            failures.append(
                f"{metric}"
                + (f" [{method}]" if method else "")
                + f": newest vs_baseline {newest:g} "
                f"({os.path.basename(newest_path)}) is "
                f"{(1 - newest / best_prior) * 100:.1f}% below the best "
                f"prior {best_prior:g}")
    return failures


def trend_table(series: dict) -> str:
    lines = []
    for (metric, method), hist in sorted(
            series.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")):
        label = metric + (f" [{method}]" if method else "")
        lines.append(label)
        for path, row in hist:
            vs = row.get("vs_baseline")
            note = ""
            if row.get("baseline_recorded"):
                note = "  (baseline recorded this run — not scored)"
            if "floor" in row:
                score = f"floor={row['floor']:g}"
            elif "ceiling" in row:
                score = f"ceiling={row['ceiling']:g}"
            elif row.get("direction") == "down":
                score = "direction=down"
            else:
                score = f"vs_baseline={'null' if vs is None else f'{vs:g}'}"
            lines.append(
                f"  {os.path.basename(path):<24} value="
                f"{row.get('value', float('nan')):>10.3f} "
                f"{row.get('unit', ''):<9} {score}{note}")
    return "\n".join(lines) if lines else "(no trajectory rows)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="*",
                    help="trajectory files (default: --dir/BENCH_r*.json "
                         "+ --dir/SLO_r*.json + --dir/REPLICA_r*.json + "
                         "--dir/FABRIC_r*.json + --dir/FLYWHEEL_r*.json "
                         "+ --dir/STREAM_r*.json + "
                         "--dir/MULTIMODEL_r*.json + "
                         "--dir/AUTOSCALE_r*.json + "
                         "--dir/CASCADE_r*.json + --dir/WATCH_r*.json)")
    ap.add_argument("--dir", default=".",
                    help="where to glob BENCH_r*.json / SLO_r*.json / "
                         "REPLICA_r*.json / FABRIC_r*.json / "
                         "FLYWHEEL_r*.json / STREAM_r*.json / "
                         "MULTIMODEL_r*.json / AUTOSCALE_r*.json / "
                         "CASCADE_r*.json / WATCH_r*.json when no paths "
                         "given")
    ap.add_argument("--threshold", type=float, default=GATE_THRESHOLD,
                    help="allowed fractional drop vs the best prior run "
                         "(default 0.10)")
    ap.add_argument("--check-format", action="store_true",
                    dest="check_format",
                    help="only validate the files parse as trajectory "
                         "rows; no gating")
    args = ap.parse_args(argv)

    paths = args.paths or (
        sorted(glob.glob(os.path.join(args.dir, "BENCH_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "SLO_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "REPLICA_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "FABRIC_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "FLYWHEEL_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "STREAM_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "MULTIMODEL_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "AUTOSCALE_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "CASCADE_r*.json")))
        + sorted(glob.glob(os.path.join(args.dir, "WATCH_r*.json"))))
    if not paths:
        print("perf_gate: no BENCH_*.json / SLO_*.json files found",
              file=sys.stderr)
        return 2

    if args.check_format:
        errors = check_format(paths)
        for e in errors:
            print(f"perf_gate: FORMAT {e}", file=sys.stderr)
        if not errors:
            print(f"perf_gate: {len(paths)} file(s) well-formed")
        return 1 if errors else 0

    series = build_series(paths)
    print(trend_table(series))
    failures = gate(series, args.threshold)
    for f in failures:
        print(f"perf_gate: REGRESSION {f}", file=sys.stderr)
    if not failures:
        print(f"perf_gate: OK ({len(paths)} run(s), threshold "
              f"{args.threshold * 100:.0f}%)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
