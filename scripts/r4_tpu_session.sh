#!/usr/bin/env bash
# Round-4 TPU measurement session — run serially the moment the tunnel is
# healthy (NEVER overlap TPU jobs; see .claude/skills/verify gotchas).
# Usage: bash scripts/r4_tpu_session.sh [logfile]
# Each step prints its own JSON/ledger lines; the log is the round-4
# evidence for: tunnel gauge, loader-inclusive window (owed 2 rounds),
# FPN bf16-IoU lever ms, VGG16 ledger, mask-eval recheck.
set -x
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/r4_tpu_session.log}
{
  echo "=== $(date -u) gauge: staged headline bench"
  python bench.py

  echo "=== $(date -u) loader-inclusive attempt 1"
  python bench.py --mode loader
  echo "=== $(date -u) loader-inclusive attempt 2"
  python bench.py --mode loader

  echo "=== $(date -u) Pallas gate + assign-kernel timing"
  python scripts/check_pallas.py

  # NOTE: at original run time ASSIGN_FUSED temporarily defaulted True;
  # it was later measured-and-rejected (config.py) so the flag is now
  # explicit to keep this leg meaning what its label says on a rerun.
  echo "=== $(date -u) FPN with fused assign kernel (opt-in)"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=True
  echo "=== $(date -u) FPN dense assign (round-3 baseline path)"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=False
  echo "=== $(date -u) FPN dense + bf16-IoU lever"
  python bench.py --network resnet101_fpn --cfg tpu__ASSIGN_FUSED=False \
      --cfg TRAIN__RPN_ASSIGN_IOU_BF16=True

  echo "=== $(date -u) VGG16 train bench"
  python bench.py --network vgg16
  echo "=== $(date -u) VGG16 infer bench"
  python bench.py --mode infer --network vgg16
  echo "=== $(date -u) VGG16 step profile (ledger attribution)"
  python scripts/profile_step.py --network vgg16

  echo "=== $(date -u) mask eval bench"
  python bench.py --mode infer-mask

  echo "=== $(date -u) loader overlap trace (fallback evidence)"
  python scripts/trace_loader.py
} 2>&1 | tee "$LOG"
