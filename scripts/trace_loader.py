#!/usr/bin/env python
"""Loader-overlap evidence (VERDICT round-3 item 2 fallback): trace the
double-buffered loader-fed train loop and report how much of the wall
window the device spent computing vs idle.

The owed number is loader-inclusive ≥ ~90% of staged; if the tunnel's
congested mode keeps eating the clean windows, this trace is the
substitute evidence — with the round-3 ``put`` hook the host→device
transfer runs on the prefetch thread and should overlap the previous
step, so device busy-fraction ≈ staged-bench busy-fraction and any gap
is dispatch, not transfer.

  python scripts/trace_loader.py [--steps 24] [--batch 1]
"""

import argparse
import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import time

import jax

import bench

ap = argparse.ArgumentParser()
ap.add_argument("--steps", type=int, default=24)
ap.add_argument("--batch", type=int, default=1)
ap.add_argument("--dir", default="/tmp/prof_loader")
args = ap.parse_args()

from mx_rcnn_tpu.data.loader import AnchorLoader

state, step, _, cfg = bench.build(args.batch)
roidb = bench._synthetic_roidb()
loader = AnchorLoader(roidb, cfg, args.batch, shuffle=True, seed=0)
loader.put = jax.device_put       # transfer on the prefetch thread
for b in loader:                  # warm every bucket
    state, m = step(state, b, jax.random.PRNGKey(0))
jax.block_until_ready(m)

shutil.rmtree(args.dir, ignore_errors=True)
n = 0
t0 = time.time()
with jax.profiler.trace(args.dir):
    for i, b in enumerate(loader):
        state, m = step(state, b, jax.random.PRNGKey(i))
        n += args.batch
        if i + 1 >= args.steps:
            break
    jax.block_until_ready(m)
wall = time.time() - t0
print(f"loader-fed: {n} imgs in {wall:.3f}s = {n / wall:.2f} imgs/s wall")

from parse_xplane import main as print_xplane

pb = glob.glob(f"{args.dir}/plugins/profile/*/*.xplane.pb")[0]
print_xplane(pb, topn=25)
print("compare: device busy-sum above vs the staged bench's device step "
      "time x steps — transfer fully overlapped means equal busy-sums "
      "and the wall gap is dispatch latency only.")
