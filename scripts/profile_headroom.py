#!/usr/bin/env python
"""Resolve the backbone-conv headroom question with profiled device time.

Round-1 left a contradiction (VERDICT round 1, "What's weak" #2):
BASELINE.md said a bare stage-3 bottleneck chain reaches ~78-94 TFLOP/s
while ROADMAP called ~16 TFLOP/s the conv ceiling.  This script measures
both claims the only trustworthy way on the tunneled chip — xplane device
time ("XLA Modules" line) + XLA's own FLOP count (compiled.cost_analysis)
— for:

  * full ResNet-101 body, fwd and fwd+bwd, at the bench shape
  * stage-3 chain (23 bottleneck units) fwd and fwd+bwd
  * one bottleneck unit fwd
  * a "bare" 3x3 conv chain (the round-1 calibration shape)

and prints per-op-family time for the body fwd+bwd so conv time vs
standalone elementwise time is explicit.

Usage: python scripts/profile_headroom.py  (needs the real chip)
"""

import collections
import glob
import os
import re
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from parse_xplane import xplane_lines
from mx_rcnn_tpu.models.backbones import ResNetConv, ResNetStage, Bottleneck

assert jax.default_backend() == "tpu", jax.default_backend()

H, W = 608, 1024
REPEAT = 10


def profile(name, fn, *args, flops=None):
    """Run fn REPEAT times under a trace; return device ms/call."""
    # warm: compile + first-chain cost off the record
    for _ in range(3):
        o = fn(*args)
    jax.block_until_ready(o)
    d = f"/tmp/headroom/{name.replace(' ', '_').replace('/', '_')}"
    shutil.rmtree(d, ignore_errors=True)
    with jax.profiler.trace(d):
        for _ in range(REPEAT):
            o = fn(*args)
        jax.block_until_ready(o)
    pbs = glob.glob(f"{d}/plugins/profile/*/*.xplane.pb")
    lines = xplane_lines(pbs[0])
    mods = lines.get("XLA Modules")
    if mods is None:
        print(f"{name:34s}  NO MODULE LINE ({list(lines)})")
        return None, None
    n, total = mods[0], mods[1]
    per_call = total / REPEAT
    tf = (flops / (per_call / 1e3) / 1e12) if flops else 0.0
    gf = (flops or 0) / 1e9
    print(f"{name:34s} {per_call:8.3f} ms/call   {gf:8.1f} GF   {tf:6.1f} TFLOP/s   ({n} ev)")
    return per_call, lines


def build(mod, x):
    params = mod.init(jax.random.PRNGKey(0), x)

    def loss(p, x):
        out = mod.apply(p, x)
        leaves = jax.tree_util.tree_leaves(out)
        return sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves)

    fwd = jax.jit(loss)

    @jax.jit
    def fwdbwd(p, x):
        l, g = jax.value_and_grad(loss)(p, x)
        return l + sum(jnp.sum(jnp.abs(t.astype(jnp.float32)))
                       for t in jax.tree_util.tree_leaves(g)) * 0.0

    fl_f = fwd.lower(params, x).compile().cost_analysis().get("flops", 0)
    fl_b = fwdbwd.lower(params, x).compile().cost_analysis().get("flops", 0)
    return params, fwd, fwdbwd, fl_f, fl_b


rng = np.random.RandomState(0)

print("=== full ResNet-101 body (s2d host layout, bench shape) ===")
x12 = jnp.asarray(rng.randn(1, H // 2, W // 2, 12), jnp.float32)
p, fwd, fwdbwd, ff, fb = build(ResNetConv(depth="resnet101"), x12)
profile("body fwd", fwd, p, x12, flops=ff)
tb, lines_b = profile("body fwd+bwd", fwdbwd, p, x12, flops=fb)

if lines_b:
    print("\n-- body fwd+bwd, per-op-family device ms (sum over "
          f"{REPEAT} calls; divide by {REPEAT}):")
    for ln in ("XLA Ops",):
        if ln in lines_b:
            for fam, ms in lines_b[ln][2].most_common(14):
                print(f"   {ms / REPEAT:8.3f} ms  {fam}")

print("\n=== stage-3 chain (23 units, 1024ch, /16) ===")
x16 = jnp.asarray(rng.randn(1, H // 8, W // 8, 512), jnp.bfloat16)
p3, fwd3, fwdbwd3, ff3, fb3 = build(ResNetStage(23, 256, 2), x16)
profile("stage3 fwd", fwd3, p3, x16, flops=ff3)
profile("stage3 fwd+bwd", fwdbwd3, p3, x16, flops=fb3)

print("\n=== one bottleneck unit (stage-3 identity shape) ===")
xu = jnp.asarray(rng.randn(1, H // 16, W // 16, 1024), jnp.bfloat16)
pu, fwdu, fwdbwdu, ffu, fbu = build(Bottleneck(256), xu)
profile("unit fwd", fwdu, pu, xu, flops=ffu)
profile("unit fwd+bwd", fwdbwdu, pu, xu, flops=fbu)


print("\n=== bare 3x3 conv chain (stage-3 spatial, 256ch) ===")


class ConvChain(nn.Module):
    n: int = 8
    f: int = 256

    @nn.compact
    def __call__(self, x):
        for i in range(self.n):
            x = nn.Conv(self.f, (3, 3), padding=[(1, 1)] * 2, use_bias=False,
                        dtype=jnp.bfloat16, name=f"c{i}")(x)
        return x


xc = jnp.asarray(rng.randn(1, H // 16, W // 16, 256), jnp.bfloat16)
pc, fwdc, fwdbwdc, ffc, fbc = build(ConvChain(), xc)
profile("bare 3x3 chain fwd", fwdc, pc, xc, flops=ffc)
profile("bare 3x3 chain fwd+bwd", fwdbwdc, pc, xc, flops=fbc)

print("\n=== matmul calibration ===")
a = jnp.asarray(rng.randn(8192, 8192), jnp.bfloat16)


@jax.jit
def mm(a):
    return a @ a


fl_mm = 2 * 8192 ** 3
profile("8k bf16 matmul", mm, a, flops=fl_mm)
