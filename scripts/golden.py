#!/usr/bin/env python
"""Golden-runway: probe → convert → run → compare, in one command.

The single biggest unproven claim in this repo is golden-mAP parity on the
real datasets (SURVEY §4: run ``script/vgg16_voc07.sh`` and compare to the
upstream README table) — blocked only because neither VOC/COCO nor ImageNet
weights exist in this environment.  This script makes that run
zero-friction the day the blocker lifts:

  python scripts/golden.py                  # probe, run everything runnable
  python scripts/golden.py --probe-only     # report availability, run nothing
  python scripts/golden.py --config vgg16_voc07
  python scripts/golden.py --fixture DIR    # full rehearsal on generated
      mini fixtures (tiny shapes, from-scratch) — the SAME probe/convert/
      run/compare code path, exercised by tests/test_golden.py so nothing
      here rots while the real data stays absent.

Probing rules (all relative to --root, default ``data``, and --model_dir,
default ``model``):
  VOC07   : {root}/VOCdevkit/VOC2007/ImageSets/Main/{trainval,test}.txt
  COCO    : {root}/coco/annotations/instances_{train2017,val2017}.json
  weights : {model_dir}/{net}_imagenet.npz, else any {model_dir}/{net}*.pth
            (torchvision state_dict) which is converted via
            mx_rcnn_tpu/utils/convert_torch.py.

Each runnable config trains with its recipe's hyperparameters
(``script/*.sh``), evaluates, and lands one row in GOLDEN.md next to the
BASELINE.md anchor.  Reference: upstream ``script/vgg16_voc07.sh`` +
README table (mount empty every session; anchors carry their confidence
tags from BASELINE.md).
"""

from __future__ import annotations

import argparse
import glob
import importlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# ---------------------------------------------------------------------------
# Golden config registry: recipe hyperparameters from script/*.sh, anchors
# from BASELINE.md (confidence tags preserved — see that file's sourcing
# caveat; the upstream README was unrecoverable, mount empty).
GOLDEN = {
    "vgg16_voc07": dict(
        network="vgg16", dataset="PascalVOC", torch_name="vgg16",
        train_set="2007_trainval", test_set="2007_test",
        epochs=10, lr=0.001, lr_step="7", batch_images=1,
        anchor=70.2, anchor_metric="VOC07 mAP",
        anchor_src="upstream README [recalled — low]; paper end2end ~70.0"),
    "resnet101_voc07": dict(
        network="resnet101", dataset="PascalVOC", torch_name="resnet101",
        train_set="2007_trainval", test_set="2007_test",
        epochs=10, lr=0.001, lr_step="7", batch_images=1,
        anchor=None, anchor_metric="VOC07 mAP",
        anchor_src="no VOC07-only anchor recovered (BASELINE.md records "
                   "79.3 for 07+12 [recalled — low])"),
    "resnet101_coco": dict(
        network="resnet101", dataset="COCO", torch_name="resnet101",
        train_set="train2017", test_set="val2017",
        epochs=8, lr=0.001, lr_step="6", batch_images=1,
        anchor=27.0, anchor_metric="COCO box AP",
        anchor_src="upstream README [recalled — low]"),
    "resnet101_fpn_coco": dict(
        network="resnet101_fpn", dataset="COCO", torch_name="resnet101",
        train_set="train2017", test_set="val2017",
        epochs=8, lr=0.001, lr_step="6", batch_images=1,
        anchor=36.5, anchor_metric="COCO box AP",
        anchor_src="FPN paper (external anchor, target config)"),
    "resnet101_fpn_mask_coco": dict(
        network="resnet101_fpn_mask", dataset="COCO", torch_name="resnet101",
        train_set="train2017", test_set="val2017",
        epochs=8, lr=0.001, lr_step="6", batch_images=1,
        anchor=35.7, anchor_metric="COCO mask AP",
        anchor_src="Mask R-CNN paper (external anchor, target config)"),
}


def _runnable(name, avail):
    c = GOLDEN[name]
    ds_key = "voc07" if c["dataset"] == "PascalVOC" else "coco"
    return avail["datasets"].get(ds_key) and (
        avail["weights"].get(c["torch_name"]) is not None)


# ---------------------------------------------------------------------------
def probe(root: str, model_dir: str) -> dict:
    """What of the golden prerequisites exists on disk right now?"""
    voc = os.path.join(root, "VOCdevkit", "VOC2007", "ImageSets", "Main")
    voc_ok = all(os.path.exists(os.path.join(voc, s + ".txt"))
                 for s in ("trainval", "test"))
    coco_ann = os.path.join(root, "coco", "annotations")
    coco_ok = all(os.path.exists(os.path.join(
        coco_ann, f"instances_{s}.json")) for s in ("train2017", "val2017"))

    # keyed by torch_name: the converted npz depends only on the backbone
    # (resnet101 serves classic, fpn and mask configs alike)
    weights = {}
    for torch_name in sorted({c["torch_name"] for c in GOLDEN.values()}):
        npz = os.path.join(model_dir, f"{torch_name}_imagenet.npz")
        if os.path.exists(npz):
            weights[torch_name] = ("npz", npz)
            continue
        pths = sorted(glob.glob(os.path.join(model_dir, torch_name + "*.pth")))
        weights[torch_name] = ("pth", pths[0]) if pths else None
    return {"datasets": {"voc07": voc_ok, "coco": coco_ok},
            "weights": weights}


def ensure_npz(torch_name: str, kind_path, model_dir: str) -> str:
    """Return a ready .npz path, converting a found .pth if that is all
    there is (reference interchange: MXNet params; ours: torchvision)."""
    kind, path = kind_path
    if kind == "npz":
        return path
    from mx_rcnn_tpu.utils.convert_torch import convert_file

    npz = os.path.join(model_dir, f"{torch_name}_imagenet.npz")
    print(f"[golden] converting {path} -> {npz}")
    convert_file(path, torch_name, npz)
    return npz


# ---------------------------------------------------------------------------
def _run_cli(module: str, main_name: str, argv):
    """Drive a repo CLI in-process (parse_args included) — one jax init and
    one jit cache for the whole golden sweep."""
    mod = importlib.import_module(module)
    old = sys.argv
    sys.argv = [module + ".py"] + [str(a) for a in argv]
    try:
        return getattr(mod, main_name)(mod.parse_args())
    finally:
        sys.argv = old


def _score(stats: dict, cfg: dict, classes=None) -> float:
    """Pull the anchor's metric out of test.py's stats dict.  ``classes``
    restricts the VOC mean to a subset (fixture mode: only 3 of the 20 VOC
    classes exist in the mini devkit)."""
    if cfg["dataset"] == "PascalVOC":
        if classes:
            return 100.0 * float(sum(stats[c] for c in classes) / len(classes))
        aps = [v for v in stats.values() if isinstance(v, (int, float))]
        return 100.0 * float(stats.get("mAP", sum(aps) / max(len(aps), 1)))
    # COCO: pred_eval returns {"bbox": {...}, "segm": {...}} COCOeval stats
    key = "segm" if "mask" in cfg["anchor_metric"].lower() else "bbox"
    block = stats.get(key, stats)
    for k in ("AP", "AP@[.5:.95]", "mAP"):
        if k in block:
            return 100.0 * float(block[k])
    raise KeyError(f"no AP key in {sorted(block)}")


def run_config(name: str, avail: dict, args, extra_cfg=(), extra_train=(),
               extra_test=(), classes=None) -> dict:
    c = GOLDEN[name]
    npz = ensure_npz(c["torch_name"], avail["weights"][c["torch_name"]],
                     args.model_dir)
    prefix = os.path.join(args.model_dir, f"golden_{name}")
    common = ["--network", c["network"], "--dataset", c["dataset"],
              "--root_path", args.root,
              "--prefix", prefix, "--devices", str(args.devices)]
    if args.dataset_path:
        common += ["--dataset_path", args.dataset_path]
    common += [a for pair in extra_cfg for a in ("--cfg", pair)]
    print(f"[golden] training {name} ({c['epochs']} epochs)")
    _run_cli("train_end2end", "train_net", common + [
        "--image_set", c["train_set"], "--pretrained", npz,
        "--end_epoch", c["epochs"], "--lr", c["lr"], "--lr_step", c["lr_step"],
        "--batch_images", c["batch_images"]] + list(extra_train))
    print(f"[golden] evaluating {name} on {c['test_set']}")
    stats = _run_cli("test", "test_rcnn", common + [
        "--image_set", c["test_set"], "--epoch", c["epochs"]]
        + list(extra_test))
    got = _score(stats, c, classes=classes)
    return {"config": name, "metric": c["anchor_metric"], "value": got,
            "anchor": c["anchor"], "anchor_src": c["anchor_src"],
            "delta": None if c["anchor"] is None else got - c["anchor"]}


# ---------------------------------------------------------------------------
def write_table(rows, path, note=""):
    lines = ["# GOLDEN — measured vs anchor", ""]
    if note:
        lines += [note, ""]
    lines += ["| config | metric | measured | anchor | delta | anchor source |",
              "|---|---|---|---|---|---|"]
    for r in rows:
        anc = "—" if r["anchor"] is None else f"{r['anchor']:.1f}"
        dlt = "—" if r["delta"] is None else f"{r['delta']:+.1f}"
        lines.append(f"| {r['config']} | {r['metric']} | {r['value']:.2f} "
                     f"| {anc} | {dlt} | {r['anchor_src']} |")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"[golden] wrote {path}")


def run_fixture(args):
    """Rehearsal mode: generate the mini fixtures, stand them in for the
    real datasets, and push them through the identical probe → convert →
    run → compare path (tiny shapes, from-scratch, fixture anchor)."""
    from tests.fixtures import FIXTURE_CLASSES, make_mini_voc

    work = os.path.abspath(args.fixture)
    root = os.path.join(work, "data")
    model_dir = os.path.join(work, "model")
    os.makedirs(model_dir, exist_ok=True)
    make_mini_voc(os.path.join(root, "VOCdevkit"))
    # stand-in "pretrained" weights: a from-scratch init saved through the
    # real npz overlay contract, so --pretrained genuinely loads something
    import jax
    import numpy as np
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models import build_model, init_params

    cfg = generate_config("resnet50", "PascalVOC")
    params = init_params(build_model(cfg), cfg, jax.random.PRNGKey(0),
                         1, (64, 96))
    from flax.traverse_util import flatten_dict

    # init_params returns the inner params tree (root keys: backbone, …);
    # keep only the backbone — exactly what an ImageNet interchange carries
    flat = {"/".join(k): np.asarray(v)
            for k, v in flatten_dict(params).items()
            if k[0] == "backbone"}
    np.savez(os.path.join(model_dir, "resnet50_imagenet.npz"), **flat)

    GOLDEN["fixture_voc"] = dict(
        network="resnet50", dataset="PascalVOC", torch_name="resnet50",
        train_set="2007_trainval", test_set="2007_minitest",
        epochs=6, lr=0.005, lr_step="5", batch_images=2,
        anchor=20.0, anchor_metric="fixture-class mean AP x100",
        anchor_src="repo CI anchor (tests/test_cli_integration.py)")
    args.root = root
    args.model_dir = model_dir
    args.dataset_path = os.path.join(root, "VOCdevkit")
    args.devices = 1  # tiny fixture batch can't shard over a forced mesh
    avail = probe(args.root, args.model_dir)
    tiny = ["tpu__SCALES=((64,96),)", "tpu__MAX_GT=8",
            "network__ANCHOR_SCALES=(2,4)",
            "network__PIXEL_STDS=(127.0,127.0,127.0)"]
    row = run_config(
        "fixture_voc",
        {"weights": {"resnet50": ("npz", os.path.join(
            model_dir, "resnet50_imagenet.npz"))},
         "datasets": avail["datasets"]},
        args,
        extra_cfg=tiny + ["TRAIN__RPN_PRE_NMS_TOP_N=200",
                          "TRAIN__RPN_POST_NMS_TOP_N=32",
                          "TRAIN__BATCH_ROIS=16",
                          "TEST__RPN_PRE_NMS_TOP_N=200",
                          "TEST__RPN_POST_NMS_TOP_N=32"],
        extra_train=["--frequent", "8"],
        classes=FIXTURE_CLASSES)  # only these 3 VOC classes exist on disk
    write_table([row], os.path.join(work, "GOLDEN.md"),
                note="Rehearsal run over generated mini fixtures "
                     "(tiny shapes, from-scratch + npz overlay) — "
                     "NOT real-data numbers.")
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", default="data")
    ap.add_argument("--model_dir", default="model")
    ap.add_argument("--dataset_path", default="",
                    help="override DATASET_PATH (default: preset)")
    ap.add_argument("--config", default="",
                    help="run just this GOLDEN config")
    ap.add_argument("--probe-only", action="store_true")
    ap.add_argument("--devices", type=int, default=1,
                    help="data-parallel devices (1 = single chip; the "
                         "golden recipes use batch_images=1, so pass "
                         "--devices N only with a matching batch)")
    ap.add_argument("--fixture", default="",
                    help="rehearsal mode: build mini fixtures under this "
                         "dir and run the identical path")
    args = ap.parse_args(argv)

    if args.fixture:
        return run_fixture(args)

    avail = probe(args.root, args.model_dir)
    print("[golden] availability:", json.dumps(avail, default=str))
    runnable = [n for n in GOLDEN if _runnable(n, avail)]
    if args.config:
        if args.config not in GOLDEN:
            raise SystemExit(f"unknown config {args.config}; "
                             f"have {sorted(GOLDEN)}")
        if args.config not in runnable:
            raise SystemExit(f"{args.config} is not runnable: missing "
                             "dataset or weights (see availability above)")
        runnable = [args.config]
    if args.probe_only or not runnable:
        if not runnable:
            print("[golden] nothing runnable — drop VOC/COCO under "
                  f"{args.root}/ and torchvision .pth (or converted .npz) "
                  f"under {args.model_dir}/, then rerun.")
        return avail
    rows = [run_config(n, avail, args) for n in runnable]
    write_table(rows, os.path.join(REPO, "GOLDEN.md"))
    print(json.dumps({"golden": rows}))
    return rows


if __name__ == "__main__":
    main()
