"""Device-profile the one-dispatch fori_loop train chain (bench_train_chain).

The chain wall measurement read 113.4 imgs/s classic = 8.8 ms/step where
the per-dispatch device profile reads 12.20 ms — a bench must not beat
its own device profile without an explanation.  This traces the chain(n)
program itself: the xplane module busy divided by n is the true per-step
device time inside the loop, and state.step is asserted to advance by
exactly n (no silently skipped iterations).  Divergence between in-loop
and per-dispatch step time = real program differences (loop-invariant
code motion, donation aliasing vs per-call buffer copies), not tunnel
artifacts.
"""

import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import jax

import bench
from parse_xplane import main as print_xplane

network = sys.argv[1] if len(sys.argv) > 1 else "resnet101"
N = 40

state, step, hbatch, cfg = bench.build(1, network, donate=False)
# bench.make_chain_fn is the ONE chain definition — this script profiles
# the exact program bench_train_chain times (a copy here once drifted is
# the bug class this script exists to catch)
chain = bench.make_chain_fn(step, jax.device_put(hbatch))


s0 = int(jax.device_get(state.step))
state = chain(state, N)  # compile + warm
s1 = int(jax.device_get(state.step))
assert s1 - s0 == N, f"chain executed {s1 - s0} steps, expected {N}"
print(f"step-count check OK: {s0} -> {s1} (+{N})")

d = "/tmp/prof_chain"
shutil.rmtree(d, ignore_errors=True)
with jax.profiler.trace(d):
    state = chain(state, N)
    _ = int(jax.device_get(state.step))

pb = glob.glob(f"{d}/plugins/profile/*/*.xplane.pb")[0]
print(f"(ONE chain({N}) call, network={network}; divide busy by {N} for "
      f"per-step device ms)")
print_xplane(pb, topn=25)
