"""Microbench: 2x2/2 max-pool backward — reduce_window (select-and-scatter
bwd) vs non-overlapping reshape+max (equality-select bwd).

Motivation (round-4 VGG16 xplane, r4_tpu_session.log): the two live
select-and-scatter ops (pool3/pool4 bwd; pool1/2 are DCE'd behind the
frozen conv1-2) cost ~1.4 ms of the 17.33 ms step.  For stride-2 2x2
windows the pools are non-overlapping, so the general overlapping-window
machinery (and its scatter-based transpose) is pure overhead.

Reference: MXNet Pooling op (cudnn max-pool bwd routes gradient to the
window argmax); the reshape form splits ties evenly — bwd-only
divergence, ledgered in BASELINE.md.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from flax import linen as nn

from mx_rcnn_tpu.ops.pool import max_pool_2x2

SHAPES = [  # the two live VGG16 bwd pools at 608x1024 input
    (1, 152, 256, 256),
    (1, 76, 128, 512),
]


def timed(f, x, n=20):
    r = f(x)
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(n):
        r = f(x)
    jax.block_until_ready(r)
    return (time.time() - t0) / n * 1000


def main():
    for shape in SHAPES:
        x = jnp.ones(shape, jnp.bfloat16) * 0.5 + \
            jax.random.normal(jax.random.PRNGKey(0), shape, jnp.bfloat16)

        def loss_rw(x):
            return nn.max_pool(x, (2, 2), strides=(2, 2)).astype(jnp.float32).sum()

        def loss_rs(x):
            return max_pool_2x2(x).astype(jnp.float32).sum()

        g_rw = jax.jit(jax.grad(loss_rw))
        g_rs = jax.jit(jax.grad(loss_rs))
        fwd_equal = bool(jnp.array_equal(
            nn.max_pool(x, (2, 2), strides=(2, 2)), max_pool_2x2(x)))
        print(f"{shape}: fwd_equal={fwd_equal} "
              f"reduce_window_bwd={timed(g_rw, x):.3f} ms "
              f"reshape_bwd={timed(g_rs, x):.3f} ms")


if __name__ == "__main__":
    main()
