#!/usr/bin/env python
"""Measure the DCE win from stop_gradient-ing frozen params (conv1 + bn1 +
stage1 + all BN affines/stats — the reference's resnet fixed_param_prefix)
in the ResNet-101 body fwd+bwd, vs the round-1 approach (grads computed for
everything, zeroed in the optimizer)."""

import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import jax
import jax.numpy as jnp
import numpy as np

from parse_xplane import xplane_lines
from mx_rcnn_tpu.models.backbones import ResNetConv
from mx_rcnn_tpu.train.optim import fixed_param_mask

assert jax.default_backend() == "tpu"

H, W = 608, 1024
REPEAT = 10

rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(1, H // 2, W // 2, 12), jnp.float32)
mod = ResNetConv(depth="resnet101")
params = mod.init(jax.random.PRNGKey(0), x)["params"]

# config.py resnet FIXED_PARAMS; fixed_param_mask joins path[1:], but here
# the backbone IS the top level, so prepend a dummy root
mask = fixed_param_mask({"backbone": params},
                        ("conv1", "bn1", "stage1", "gamma", "beta"))["backbone"]
n_frozen = sum(not m for m in jax.tree.leaves(mask))
print(f"frozen leaves: {n_frozen}/{len(jax.tree.leaves(mask))}")


def make_fwdbwd(stop_frozen):
    def loss(p, x):
        if stop_frozen:
            p = jax.tree.map(
                lambda v, t: v if t else jax.lax.stop_gradient(v), p, mask)
        out = mod.apply({"params": p}, x)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    @jax.jit
    def fwdbwd(p, x):
        l, g = jax.value_and_grad(loss)(p, x)
        return l + sum(jnp.sum(jnp.abs(t.astype(jnp.float32)))
                       for t in jax.tree.leaves(g)) * 0.0

    return fwdbwd


for name, stop in (("mask-in-optimizer (round 1)", False),
                   ("stop_gradient frozen (DCE)", True)):
    fn = make_fwdbwd(stop)
    for _ in range(3):
        o = fn(params, x)
    jax.block_until_ready(o)
    d = f"/tmp/dce/{stop}"
    shutil.rmtree(d, ignore_errors=True)
    with jax.profiler.trace(d):
        for _ in range(REPEAT):
            o = fn(params, x)
        jax.block_until_ready(o)
    pb = glob.glob(f"{d}/plugins/profile/*/*.xplane.pb")[0]
    mods = xplane_lines(pb).get("XLA Modules")
    print(f"{name:32s} {mods[1] / REPEAT:7.3f} ms/call")
