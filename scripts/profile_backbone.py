#!/usr/bin/env python
"""Per-component backbone timing on the real chip.

Times fwd and fwd+bwd of the ResNet-101 conv body and its pieces at the
bench shape (1, 608, 1024, 3) to locate where the conv-bound ~19 ms goes
(ROADMAP: conv ceiling investigation).  Chained-steps timing with a
scalar readback fence (fetching activations over the tunnel would dominate).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import flax.linen as nn
from mx_rcnn_tpu.models.backbones import ResNetConv, ResNetStage

assert jax.default_backend() == "tpu"

H, W = 608, 1024
REPEAT = 20


def timeit(fn, *args):
    # warm up with a full chain: on the tunneled device the first chain
    # after compile pays a large one-time cost (~300 ms/call), and single
    # blocked calls pay ~100 ms dispatch latency; only the second-or-later
    # chained run measures device time
    best = None
    for _ in range(3):
        t0 = time.time()
        for _ in range(REPEAT):
            out = fn(*args)
        _ = float(jax.device_get(out))  # scalar fence
        dt = (time.time() - t0) / REPEAT * 1000
        best = dt if best is None else min(best, dt)
    return best


def bench_module(name, mod, x):
    params = mod.init(jax.random.PRNGKey(0), x)

    def loss(p, x):
        out = mod.apply(p, x)
        leaves = jax.tree_util.tree_leaves(out)
        return sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves)

    fwd = jax.jit(loss)

    @jax.jit
    def fwdbwd(p, x):
        l, g = jax.value_and_grad(loss)(p, x)
        return l + sum(jnp.sum(jnp.abs(t.astype(jnp.float32)))
                       for t in jax.tree_util.tree_leaves(g)) * 0.0

    tf = timeit(fwd, params, x)
    tb = timeit(fwdbwd, params, x)
    print(f"{name:30s} fwd {tf:6.2f} ms   fwd+bwd {tb:6.2f} ms")
    return tf, tb


class Stem(nn.Module):
    """Stem as built by ResNetConv (StemConvS2D) or, for comparison, the
    direct 7×7/2 conv it replaced (``s2d=False`` — the BASELINE.md stem
    numbers are this pair)."""

    pool: bool = True
    s2d: bool = True

    @nn.compact
    def __call__(self, x):
        x = x.astype(jnp.bfloat16)
        if self.s2d:
            from mx_rcnn_tpu.models.backbones import StemConvS2D

            x = StemConvS2D(name="conv1")(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3)] * 2,
                        use_bias=False, dtype=jnp.bfloat16, name="conv1")(x)
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1)] * 2)
        return x


rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(1, H, W, 3), jnp.float32)
bench_module("full r101 body (s1-4)", ResNetConv(depth="resnet101"), x)
bench_module("stem s2d (conv1+pool)", Stem(), x)
bench_module("stem direct (replaced)", Stem(s2d=False), x)
bench_module("conv1 s2d only", Stem(pool=False), x)
bench_module("conv1 direct only", Stem(pool=False, s2d=False), x)

x4 = jnp.asarray(rng.randn(1, H // 4, W // 4, 64), jnp.bfloat16)
bench_module("stage1 (3u, 256ch, /4)", ResNetStage(3, 64, 1), x4)
x8in = jnp.asarray(rng.randn(1, H // 4, W // 4, 256), jnp.bfloat16)
bench_module("stage2 (4u, 512ch, /8)", ResNetStage(4, 128, 2), x8in)
x16in = jnp.asarray(rng.randn(1, H // 8, W // 8, 512), jnp.bfloat16)
bench_module("stage3 (23u, 1024ch, /16)", ResNetStage(23, 256, 2), x16in)
