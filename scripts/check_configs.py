#!/usr/bin/env python
"""One train step + one predict for EVERY network preset on the real TPU.

The pytest suite runs on the virtual CPU mesh (tests/conftest.py), where
Mosaic kernels delegate to oracles and XLA lowers differently — so a
config can pass the suite yet fail to compile or run on the chip.  This
sweep catches that per preset.  Tiny shapes keep each compile short.

Exits nonzero on the first failure.
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config, list_networks
from mx_rcnn_tpu.data.image import space_to_depth2
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.train import create_train_state, make_train_step

assert jax.default_backend() == "tpu", "run on the TPU chip"

H, W, G = 64, 96, 4
PRESETS = list_networks()  # every preset — a new one must compile on-chip


def tiny_cfg(name):
    cfg = generate_config(
        name, "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
        TEST__RPN_PRE_NMS_TOP_N=128, TEST__RPN_POST_NMS_TOP_N=32,
    )
    return cfg.replace(
        network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                                    PIXEL_STDS=(127.0,) * 3),
        tpu=dataclasses.replace(cfg.tpu, SCALES=((H, W),), MAX_GT=G))


def make_batch(cfg):
    rng = np.random.RandomState(0)
    images = rng.randn(1, H, W, 3).astype(np.float32)
    if cfg.network.HOST_S2D:
        images = np.stack([space_to_depth2(im) for im in images])
    gtb = np.zeros((1, G, 4), np.float32)
    gtc = np.zeros((1, G), np.int32)
    gtv = np.zeros((1, G), bool)
    gtb[0, 0] = (10, 10, 50, 50)
    gtc[0, 0] = 1
    gtv[0, 0] = True
    batch = dict(images=images,
                 im_info=np.asarray([[H, W, 1.0]], np.float32),
                 gt_boxes=gtb, gt_classes=gtc, gt_valid=gtv)
    if cfg.network.HAS_MASK:
        batch["gt_masks"] = np.zeros((1, G, 28, 28), np.float32)
    return batch


fails = 0
for name in PRESETS:
    try:
        cfg = tiny_cfg(name)
        model = build_model(cfg)
        params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (H, W))
        state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
        step = make_train_step(model, tx, trainable_mask=mask)
        batch = make_batch(cfg)
        state, m = step(state, batch, jax.random.PRNGKey(1))
        loss = float(jax.device_get(m["total_loss"]))
        assert np.isfinite(loss), loss

        pred = jax.jit(lambda p, x, i: model.apply({"params": p}, x, i,
                                                   method=model.predict))
        out = pred(state.params, batch["images"], batch["im_info"])
        jax.block_until_ready(out)
        finite = all(bool(np.all(np.isfinite(np.asarray(jax.device_get(l))
                                             .astype(np.float64))))
                     for l in jax.tree_util.tree_leaves(out))
        assert finite
        print(f"{name:22s} OK  train loss={loss:.3f}")
    except Exception as e:
        fails += 1
        print(f"{name:22s} FAIL  {type(e).__name__}: {str(e)[:200]}")

print("configs:", "FAIL" if fails else "OK")
raise SystemExit(1 if fails else 0)
