#!/usr/bin/env python
"""Fold telemetry JSONL event streams into the human table and a
BENCH_*.json-compatible summary.

  python scripts/telemetry_report.py RUN_DIR              # all ranks' files
  python scripts/telemetry_report.py a/events_rank0.jsonl b/events_rank0.jsonl
  python scripts/telemetry_report.py RUN_DIR --json agg.json   # aggregate out
  python scripts/telemetry_report.py RUN_DIR --bench           # metric rows
  python scripts/telemetry_report.py RUN_DIR --trace out.json  # Perfetto

Accepts any mix of run directories (expanded to every events_rank*.jsonl
inside — the multi-host layout) and explicit event files; multiple runs
fold into one aggregate, which is how the bench trajectory accumulates
across sessions.  Pure host-side JSON folding: no jax import, safe on a
machine with no accelerator.

The table includes a "recovery event" section (loader/bad_record,
train/nan_*, train/preempted, checkpoint/retry — zeros included) so
fault-tolerance triage reads off one block; script/fault_smoke.sh
asserts on it.  Streams from a serving run (serve.py / bench.py --mode
serve) additionally get a "serve health" section — requests/batches plus
the rejection, deadline-exceeded, and post-warmup recompile counters,
zeros included — which script/serve_smoke.sh asserts on the same way.
Streams from a fabric router (serve.py --fabric) get a "fabric health"
section on top: membership churn (member_joined / member_evicted /
member_quarantined), circuit-breaker opens, hedges fired/won, retries,
partitions, and rolling reloads, zeros included;
script/fabric_smoke.sh asserts on it.  Streams from a model pool
(serve.py --models) get a "model pool" section: weight page-in/out and
cross-model scheduler counters plus the per-model paging variants,
zeros included; script/multimodel_smoke.sh asserts on it.

Streams carrying ``pipeline_cell`` meta rows — a live run of ``bench.py
--mode pipeline``, or its ``--sweep-out`` JSONL passed directly as a
path — get a "pipeline cell" section: one row per sweep cell (fastest
first) with imgs/s and the loader_wait / assembly_wait / dispatch
breakdown, so "which knob moved the needle and where did the time go"
reads off one table; script/pipeline_smoke.sh asserts on it.

Streams carrying ``eval_pipeline`` meta rows (any ``pred_eval`` run —
test.py, bench.py --mode eval, script/eval_smoke.sh) get an "eval
pipeline" section: one row per eval run with imgs/s, wall time, the
loader / readback / host-post-process wait split and the overlap
fraction (how much host post-process hid under the device forward), so
serial-vs-pipelined-vs-device-postprocess comparisons read off one
table.

Run dirs also expand distributed-trace span streams
(``spans_<member>.jsonl``, a serve.py --trace run): a "tracing" counter
section appears, and ``--trace out.json`` folds the cross-hop spans
into per-member Perfetto process groups with flow arrows linking each
trace id across hops (per-trace forensics: scripts/trace_query.py).

Run dirs also expand watchtower transition logs
(``alerts_<member>.jsonl``, a serve.py --watch run): an "alerts"
section appears — per alertname, how often it went pending / firing /
resolved / silenced and the total time spent firing, cross-member —
so "what paged, how often, for how long" reads off one table
(per-alert forensics: scripts/alert_query.py).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.telemetry.report import (aggregate, bench_rows, load_events,
                                          render_table)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="+",
                    help="run directories and/or events_rank*.jsonl files")
    ap.add_argument("--json", default="",
                    help="also write the aggregated summary JSON here")
    ap.add_argument("--bench", action="store_true",
                    help="print one BENCH-compatible JSON line per rate "
                         "gauge instead of the table")
    ap.add_argument("--trace", default="",
                    help="also fold the events into Chrome/Perfetto "
                         "trace_event JSON here (open in "
                         "https://ui.perfetto.dev)")
    args = ap.parse_args()

    events = load_events(args.paths)
    summary = aggregate(events)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
    if args.trace:
        from mx_rcnn_tpu.telemetry.trace import write_chrome_trace

        n = write_chrome_trace(events, args.trace)
        print(f"wrote {n} trace events to {args.trace}")
    if args.bench:
        for row in bench_rows(summary):
            print(json.dumps(row))
    else:
        print(render_table(summary))


if __name__ == "__main__":
    main()
