#!/usr/bin/env python
"""Profile the flagship bench train step (device time, per-op families)."""

import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import jax

import bench
from parse_xplane import main as print_xplane

REPEAT = 10

state, step, batch, _ = bench.build()
batch = jax.device_put(batch)
key = jax.random.PRNGKey(7)

for _ in range(3):
    state, metrics = step(state, batch, key)
jax.block_until_ready(metrics)

d = "/tmp/prof_step"
shutil.rmtree(d, ignore_errors=True)
with jax.profiler.trace(d):
    for _ in range(REPEAT):
        state, metrics = step(state, batch, key)
    jax.block_until_ready(metrics)

pb = glob.glob(f"{d}/plugins/profile/*/*.xplane.pb")[0]
print(f"(sums over {REPEAT} calls)")
print_xplane(pb, topn=40)
