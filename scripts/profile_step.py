#!/usr/bin/env python
"""Profile a bench train step (device time, per-op families).

  python scripts/profile_step.py                      # classic resnet101
  python scripts/profile_step.py --network vgg16      # VGG16 ledger run
  python scripts/profile_step.py --network resnet101_fpn \
      --cfg TRAIN__RPN_ASSIGN_IOU_BF16=True           # lever A/B
"""

import argparse
import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import jax

import bench
from parse_xplane import main as print_xplane

ap = argparse.ArgumentParser()
ap.add_argument("--network", default="resnet101")
ap.add_argument("--mode", default="train", choices=("train", "infer"),
                help="train = jitted train step; infer = Predictor.predict "
                     "(the test.py eval graph — round-4 addition after the "
                     "mask-target profile surprise showed eval graphs were "
                     "never device-profiled)")
ap.add_argument("--batch", type=int, default=1)
ap.add_argument("--repeat", type=int, default=10)
ap.add_argument("--topn", type=int, default=40)
ap.add_argument("--cfg", action="append", default=[],
                help="config override PATH=VALUE (python literal)")
ap.add_argument("--dir", default="/tmp/prof_step")
args = ap.parse_args()
from mx_rcnn_tpu.tools.common import parse_cfg_overrides

bench.CFG_OVERRIDES.update(parse_cfg_overrides(args.cfg))

if args.mode == "train":
    state, step, batch, _ = bench.build(args.batch, args.network)
    batch = jax.device_put(batch)
    key = jax.random.PRNGKey(7)

    def run():
        global state
        state, metrics = step(state, batch, key)
        return metrics
else:
    pred, cfg = bench.build_infer(args.batch, args.network)
    hbatch = bench.synthetic_batch(cfg, args.batch)
    images = jax.device_put(hbatch["images"])
    im_info = jax.device_put(hbatch["im_info"])

    def run():
        return pred.predict(images, im_info)

for _ in range(3):
    out = run()
jax.block_until_ready(out)

shutil.rmtree(args.dir, ignore_errors=True)
with jax.profiler.trace(args.dir):
    for _ in range(args.repeat):
        out = run()
    jax.block_until_ready(out)

pb = glob.glob(f"{args.dir}/plugins/profile/*/*.xplane.pb")[0]
print(f"(sums over {args.repeat} calls, network={args.network}, "
      f"mode={args.mode}, cfg={args.cfg})")
print_xplane(pb, topn=args.topn)
