#!/usr/bin/env python
"""Single-image demo (reference ``demo.py``): load image → resize to the
scale bucket → forward → bbox decode + per-class NMS → print/draw boxes."""

from __future__ import annotations

import argparse

import numpy as np

from mx_rcnn_tpu.data.image import get_image, resize_to_bucket, transform_image
from mx_rcnn_tpu.eval import Predictor, im_detect
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.native import nms
from mx_rcnn_tpu.tools.common import (add_common_args, config_from_args,
                                      load_eval_params)


def parse_args():
    parser = argparse.ArgumentParser(description="Demo: detect one image")
    add_common_args(parser, train=False)
    parser.add_argument("--image", required=True)
    parser.add_argument("--out", default="",
                        help="write visualization to this path")
    parser.set_defaults(thresh=0.5)  # visualization default (reference demo)
    return parser.parse_args()


def demo_net(args):
    cfg = config_from_args(args, train=False)
    model = build_model(cfg)
    params = load_eval_params(args, cfg, model)
    predictor = Predictor(model, params, cfg)

    im = get_image(args.image)
    orig = im.copy()
    im = transform_image(im, cfg.network.PIXEL_MEANS, cfg.network.PIXEL_STDS)
    stride = max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE)
    padded, s, (eh, ew) = resize_to_bucket(im, cfg.tpu.SCALES[0], stride)
    batch = dict(images=padded[None],
                 im_info=np.asarray([[eh, ew, s]], np.float32),
                 batch_valid=np.asarray([True]))
    (scores, boxes, valid), = im_detect(predictor, batch)

    from mx_rcnn_tpu.data.pascal_voc import VOC_CLASSES

    if cfg.NUM_CLASSES == len(VOC_CLASSES):
        classes = list(VOC_CLASSES)
    else:
        classes = [f"class{i}" for i in range(cfg.NUM_CLASSES)]

    all_dets = []
    v = np.asarray(valid, bool)
    for k in range(1, cfg.NUM_CLASSES):
        sel = (scores[:, k] > args.thresh) & v
        dets = np.hstack([boxes[sel, 4 * k:4 * (k + 1)],
                          scores[sel, k][:, None]]).astype(np.float32)
        keep = nms(dets, cfg.TEST.NMS)
        for d in dets[keep]:
            all_dets.append((classes[k], d))
            logger.info("%s: %.3f at [%.1f, %.1f, %.1f, %.1f]",
                        classes[k], d[4], *d[:4])

    if args.out:
        import cv2

        from mx_rcnn_tpu.eval.tester import draw_detections

        img = cv2.cvtColor(orig, cv2.COLOR_RGB2BGR)
        draw_detections(img, all_dets)
        cv2.imwrite(args.out, img)
        logger.info("wrote %s (%d detections)", args.out, len(all_dets))
    return all_dets


if __name__ == "__main__":
    demo_net(parse_args())
