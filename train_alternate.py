#!/usr/bin/env python
"""4-step alternate Faster R-CNN training (reference ``train_alternate.py``):

1. train RPN from pretrained
2. generate proposals with the trained RPN
3. train Fast-RCNN on the cached proposals
4. train RPN round 2 — shared conv frozen (FIXED_PARAMS_SHARED)
5. proposals round 2
6. train Fast-RCNN round 2 — shared conv frozen
7. combine_model → single deployment checkpoint

Runs in-process (the reference shells out per stage); each stage reuses the
previous stage's params exactly like the reference's load_param chain.

``--tuned-pipeline`` (tools/common.config_from_args) applies the persisted
input-pipeline cell from ``bench.py --mode pipeline --auto-tune`` before
any stage runs; ``stage_args`` copies of ``args`` carry the tuned
``steps_per_dispatch`` into every fit-based stage, and the tuned loader
knobs (workers/prefetch/device-prep) ride the shared ``cfg``.  Proposal
stages (2/5) go through TestLoader, which always uses the host
preprocessing path regardless of ``--device-prep``.
"""

from __future__ import annotations

import argparse

import jax

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (add_common_args, config_from_args,
                                      get_imdb, get_train_roidb,
                                      init_or_load_params,
                                      start_observability)
from mx_rcnn_tpu.tools.test_rpn import test_rpn
from mx_rcnn_tpu.tools.train_rcnn import train_rcnn
from mx_rcnn_tpu.tools.train_rpn import train_rpn
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
from mx_rcnn_tpu.utils import combine_model


def parse_args():
    parser = argparse.ArgumentParser(description="Train Faster R-CNN alternately")
    add_common_args(parser, train=True)
    parser.add_argument("--rpn_epochs", type=int, default=None,
                        help="epochs per RPN stage (default: end_epoch)")
    parser.add_argument("--rcnn_epochs", type=int, default=None,
                        help="epochs per RCNN stage (default: end_epoch)")
    return parser.parse_args()


def alternate_train(args):
    if (getattr(args, "dist_auto", False)
            or getattr(args, "dist_coordinator", None) is not None
            or getattr(args, "dist_num_processes", None) is not None
            or getattr(args, "dist_process_id", None) is not None):
        raise NotImplementedError(
            "alternate training is single-process: stages 2/5 dump "
            "proposals through the eval path, which has no multi-host "
            "mode.  Run the train stages multi-host individually "
            "(tools/train_rpn.py, tools/train_rcnn.py --dist-*) or use "
            "train_end2end.py --dist-*")
    cfg = config_from_args(args, train=True)
    if cfg.network.HAS_MASK:
        raise NotImplementedError(
            "alternate training has no mask-target path; train mask configs "
            "end2end (train_end2end.py)")
    imdb = get_imdb(args, cfg)
    roidb = get_train_roidb(imdb, cfg)
    model = build_model(cfg)
    params = init_or_load_params(args, cfg, model, 1)
    rpn_ep = args.rpn_epochs or args.end_epoch
    rcnn_ep = args.rcnn_epochs or args.end_epoch

    def stage_args(end_epoch):
        a = argparse.Namespace(**vars(args))
        a.begin_epoch, a.end_epoch, a.prefix = 0, end_epoch, None
        return a

    # one obs plane across every stage (inert without --obs-port) — the
    # per-stage fits reuse the plane's sink instead of opening their own,
    # so a scrape mid-run sees the whole alternate sequence accumulate
    obs = start_observability(args, "train_alternate",
                              run_meta={"network": args.network})
    try:
        logger.info("=== stage 1: train RPN ===")
        s1 = train_rpn(stage_args(rpn_ep), cfg=cfg, params=params,
                       roidb=roidb)
        logger.info("=== stage 2: generate proposals ===")
        roidb = test_rpn(args, cfg=cfg, params=jax.device_get(s1.params),
                         imdb=imdb, roidb=roidb)
        logger.info("=== stage 3: train RCNN on proposals ===")
        s3 = train_rcnn(stage_args(rcnn_ep), cfg=cfg, params=params,
                        roidb=roidb)
        logger.info("=== stage 4: train RPN round 2 (shared conv frozen) ===")
        s4 = train_rpn(stage_args(rpn_ep), cfg=cfg,
                       params=jax.device_get(s3.params), roidb=roidb,
                       frozen_shared=True)
        logger.info("=== stage 5: proposals round 2 ===")
        roidb = test_rpn(args, cfg=cfg, params=jax.device_get(s4.params),
                         imdb=imdb, roidb=roidb)
        logger.info("=== stage 6: train RCNN round 2 (shared conv frozen) ===")
        s6 = train_rcnn(stage_args(rcnn_ep), cfg=cfg,
                        params=jax.device_get(s4.params), roidb=roidb,
                        frozen_shared=True)
        logger.info("=== stage 7: combine_model ===")
        final = combine_model(jax.device_get(s4.params),
                              jax.device_get(s6.params))
        mgr = CheckpointManager(args.prefix)
        mgr.save_epoch(args.end_epoch, final, cfg, step=0)
        logger.info("combined checkpoint saved to %s", args.prefix)
    finally:
        obs.close()
    return final


if __name__ == "__main__":
    alternate_train(parse_args())
